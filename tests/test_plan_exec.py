"""Mesh plan executor: the production query DSL over an 8-device mesh.

VERDICT r1 item 2: the distributed program must be the ENGINE, not a demo
kernel — arbitrary query-DSL plans execute as one multi-device shard_map
program, with results identical to the single-node per-segment path merged
host-side (SearchPhaseController.java:408 semantics).
"""

import numpy as np
import pytest

import jax

from elasticsearch_tpu.analysis.analyzers import AnalysisRegistry
from elasticsearch_tpu.index.segment import SegmentBuilder
from elasticsearch_tpu.mapper.mapping import MapperService
from elasticsearch_tpu.parallel.mesh import shard_mesh
from elasticsearch_tpu.parallel.plan_exec import (
    MeshPlanExecutor,
    PlanStructureMismatch,
    stack_plans,
)
from elasticsearch_tpu.search import plan as P
from elasticsearch_tpu.search.query_dsl import ShardQueryContext, parse_query


MAPPING = {
    "properties": {
        "body": {"type": "text", "analyzer": "whitespace"},
        "n": {"type": "integer"},
        "tag": {"type": "keyword"},
        "price": {"type": "float"},
    }
}



@pytest.fixture(autouse=True)
def _scatter_plans(monkeypatch):
    """Most of this module pins the SCATTER mesh formulation so its
    parity tests stay deterministic and fast; TestMeshPallasPlane below
    overrides to "interpret" to exercise the tile kernel INSIDE the mesh
    program. (_pallas_mode reads ES_TPU_PALLAS at call time — import
    order is irrelevant.)"""
    monkeypatch.setenv("ES_TPU_PALLAS", "off")


def build_corpus(n_shards, docs_per_shard, seed=0):
    """Sharded corpus with text + numeric + keyword fields. Every query
    term below appears on every shard (dense vocab)."""
    rng = np.random.RandomState(seed)
    vocab = [f"w{i}" for i in range(12)]
    tags = ["red", "green", "blue", "black"]
    svc = MapperService(AnalysisRegistry(), MAPPING)
    segments, ctxs = [], []
    for s in range(n_shards):
        b = SegmentBuilder(f"shard{s}")
        for d in range(docs_per_shard):
            toks = [vocab[rng.randint(len(vocab))]
                    for _ in range(rng.randint(3, 15))]
            doc = {
                "body": " ".join(toks),
                "n": int(rng.randint(0, 50)),
                "tag": tags[rng.randint(len(tags))],
                "price": float(rng.rand() * 100),
            }
            b.add_document(svc.parse_document(f"{s}-{d}", doc), d)
        segments.append(b.seal())
        ctxs.append(ShardQueryContext(svc))
    return segments, ctxs


def host_reference(segments, ctxs, query_body, k):
    """Single-node path: per-segment P.execute + host top-k merge."""
    qb = parse_query(query_body)
    rows = []
    total = 0
    for sid, (seg, ctx) in enumerate(zip(segments, ctxs)):
        node = qb.to_plan(ctx, seg)
        scores_d, matched_d = P.execute(seg.device_arrays(), node)
        scores = np.asarray(scores_d)
        matched = np.asarray(matched_d)
        live1 = np.concatenate([seg.live, np.zeros(1, bool)])
        matched = matched & live1
        total += int(matched.sum())
        for doc in np.nonzero(matched)[0]:
            rows.append((float(scores[doc]), sid, int(doc)))
    rows.sort(key=lambda r: (-r[0], r[1], r[2]))
    return total, rows[:k]


def mesh_result(executor, segments, ctxs, query_body, k):
    qb = parse_query(query_body)
    plans = [qb.to_plan(ctx, seg) for seg, ctx in zip(segments, ctxs)]
    scores, shards, docs, total = executor.execute(plans, k)[:4]
    got = [(float(s), int(sh), int(d))
           for s, sh, d in zip(np.asarray(scores), np.asarray(shards),
                               np.asarray(docs)) if s > -np.inf]
    return int(total), got


QUERY_MATRIX = [
    {"term": {"body": "w3"}},
    {"match": {"body": "w1 w4 w7"}},
    {"match_all": {}},
    {"range": {"n": {"gte": 10, "lt": 35}}},
    {"terms": {"tag": ["red", "blue"]}},
    {"exists": {"field": "n"}},
    {"bool": {
        "must": [{"match": {"body": "w2 w5"}}],
        "filter": [{"range": {"n": {"gte": 5}}}],
        "must_not": [{"term": {"tag": "black"}}],
    }},
    {"bool": {
        "should": [{"term": {"body": "w0"}}, {"term": {"body": "w9"}},
                   {"term": {"tag": "green"}}],
        "minimum_should_match": 2,
    }},
    {"constant_score": {"filter": {"range": {"price": {"lte": 50.0}}},
                        "boost": 2.5}},
    {"dis_max": {"queries": [{"term": {"body": "w1"}},
                             {"term": {"body": "w2"}}],
                 "tie_breaker": 0.3}},
    {"match_phrase": {"body": "w1 w2"}},
    {"function_score": {"query": {"match": {"body": "w3 w6"}},
                        "field_value_factor": {"field": "price"},
                        "boost_mode": "multiply"}},
]


@pytest.fixture(scope="module")
def corpus8():
    return build_corpus(8, 60)


@pytest.fixture(scope="module")
def executor8(corpus8):
    segments, _ = corpus8
    return MeshPlanExecutor(segments, shard_mesh(8))


class TestMeshPlanParity:
    @pytest.mark.parametrize("query", QUERY_MATRIX,
                             ids=[list(q)[0] + str(i)
                                  for i, q in enumerate(QUERY_MATRIX)])
    def test_parity_with_host_path(self, corpus8, executor8, query):
        segments, ctxs = corpus8
        ref_total, ref_rows = host_reference(segments, ctxs, query, k=10)
        got_total, got_rows = mesh_result(executor8, segments, ctxs, query,
                                          k=10)
        assert got_total == ref_total
        # same scores in order; doc identity may permute within exact ties
        ref_scores = [r[0] for r in ref_rows]
        got_scores = [r[0] for r in got_rows]
        assert got_scores == pytest.approx(ref_scores, rel=1e-5)
        # same (shard, doc) set wherever scores are distinct
        assert {(s, d) for sc, s, d in got_rows if got_scores.count(sc) == 1} \
            == {(s, d) for sc, s, d in ref_rows if ref_scores.count(sc) == 1}

    def test_uneven_shard_sizes(self):
        segments, ctxs = build_corpus(3, 10, seed=5)
        big_seg, big_ctx = build_corpus(1, 400, seed=6)
        segments.append(big_seg[0])
        ctxs.append(big_ctx[0])
        ex = MeshPlanExecutor(segments, shard_mesh(8))
        q = {"bool": {"must": [{"match": {"body": "w1 w2"}}],
                      "filter": [{"range": {"n": {"gte": 1}}}]}}
        ref_total, ref_rows = host_reference(segments, ctxs, q, k=7)
        got_total, got_rows = mesh_result(ex, segments, ctxs, q, k=7)
        assert got_total == ref_total
        assert [r[0] for r in got_rows] == pytest.approx(
            [r[0] for r in ref_rows], rel=1e-5)

    def test_fewer_shards_than_devices(self):
        segments, ctxs = build_corpus(3, 30, seed=2)
        ex = MeshPlanExecutor(segments, shard_mesh(8))
        q = {"match": {"body": "w4"}}
        ref_total, ref_rows = host_reference(segments, ctxs, q, k=10)
        got_total, got_rows = mesh_result(ex, segments, ctxs, q, k=10)
        assert got_total == ref_total
        assert [r[0] for r in got_rows] == pytest.approx(
            [r[0] for r in ref_rows], rel=1e-5)

    def test_program_cached_across_same_shape_queries(self, corpus8,
                                                      executor8):
        from elasticsearch_tpu.parallel.plan_exec import _mesh_query_program

        segments, ctxs = corpus8
        mesh_result(executor8, segments, ctxs, {"term": {"body": "w5"}}, 10)
        info1 = _mesh_query_program.cache_info()
        mesh_result(executor8, segments, ctxs, {"term": {"body": "w6"}}, 10)
        info2 = _mesh_query_program.cache_info()
        assert info2.misses == info1.misses  # same structure -> cache hit

    def test_structure_mismatch_raises(self):
        segments, ctxs = build_corpus(2, 10, seed=3)
        qb = parse_query({"term": {"body": "w1"}})
        plans = [qb.to_plan(ctxs[0], segments[0]),
                 parse_query({"match_all": {}}).to_plan(ctxs[1], segments[1])]
        with pytest.raises(PlanStructureMismatch):
            stack_plans(plans, [s.nd_pad for s in segments], 1024, 8)


class TestIndexMeshAggsSort:
    """Index-level mesh path with aggregations and field sort: the mesh
    program computes matched/scores per device; aggregations reduce over
    those views with the host framework (full agg-type parity), and
    single-field f32-exact numeric sorts rank in-program (VERDICT r3
    item 4: UNSUPPORTED must shrink by aggs + sort)."""

    BODY = {
        "mappings": {"properties": {
            "body": {"type": "text", "analyzer": "whitespace"},
            "n": {"type": "integer"},
            "tag": {"type": "keyword"},
            "price": {"type": "float"},
        }}
    }

    def _mk(self, name, mesh):
        from elasticsearch_tpu.common.settings import Settings
        from elasticsearch_tpu.index.index_service import IndexService

        idx = IndexService(name, Settings({
            "index.number_of_shards": 3,
            "index.search.mesh": mesh,
            # no background NRT refresh: a refresh sneaking between
            # index_doc calls under suite load seals extra segments,
            # pushing (shard, segment) pairs past the 8-device mesh and
            # flaking the mesh-served assertion
            "index.refresh_interval": -1,
        }), mapping=self.BODY["mappings"])
        rng = np.random.RandomState(11)
        vocab = [f"w{i}" for i in range(10)]
        tags = ["red", "green", "blue"]
        for d in range(60):
            doc = {
                "body": " ".join(vocab[rng.randint(len(vocab))]
                                 for _ in range(6)),
                "tag": tags[rng.randint(len(tags))],
                "price": d * 0.5,  # unique + f32-exact
            }
            if d % 7 != 0:  # leave some docs without n (missing policy)
                doc["n"] = int(rng.randint(0, 40))
            idx.index_doc(str(d), doc)
        idx.refresh()
        return idx

    @pytest.fixture()
    def pair(self):
        mesh_idx = self._mk("meshagg", True)
        host_idx = self._mk("hostagg", False)
        yield mesh_idx, host_idx
        mesh_idx.close()
        host_idx.close()

    def test_aggs_parity_and_mesh_used(self, pair):
        mesh_idx, host_idx = pair
        body = {
            "query": {"match": {"body": "w1 w4"}},
            "size": 5,
            "aggs": {
                "tags": {"terms": {"field": "tag"},
                         "aggs": {"avg_n": {"avg": {"field": "n"}}}},
                "card": {"cardinality": {"field": "tag"}},
                "price_stats": {"stats": {"field": "price"}},
            },
        }
        got = mesh_idx.search(dict(body))
        want = host_idx.search(dict(body))
        assert mesh_idx._mesh_search is not None
        assert mesh_idx._mesh_search.query_total >= 1
        assert got["hits"]["total"] == want["hits"]["total"]
        assert got["aggregations"] == want["aggregations"]
        assert ([h["_id"] for h in got["hits"]["hits"]]
                == [h["_id"] for h in want["hits"]["hits"]])

    def test_sort_parity(self, pair):
        mesh_idx, host_idx = pair
        body = {
            "query": {"match_all": {}},
            "sort": [{"price": {"order": "desc"}}],
            "size": 8,
        }
        got = mesh_idx.search(dict(body))
        want = host_idx.search(dict(body))
        assert mesh_idx._mesh_search.query_total >= 1
        assert ([h["_id"] for h in got["hits"]["hits"]]
                == [h["_id"] for h in want["hits"]["hits"]])
        assert ([h["sort"] for h in got["hits"]["hits"]]
                == [h["sort"] for h in want["hits"]["hits"]])
        assert got["hits"]["max_score"] is None

    def test_sort_missing_policy(self, pair):
        mesh_idx, host_idx = pair
        for missing in ("_last", "_first", 7):
            body = {
                "query": {"match_all": {}},
                "sort": [{"n": {"order": "asc", "missing": missing}}],
                "size": 60,
            }
            got = mesh_idx.search(dict(body))
            want = host_idx.search(dict(body))
            # ties on n are order-ambiguous between paths; compare the
            # sort-value sequence (the ranking contract), not doc ids
            assert ([h["sort"] for h in got["hits"]["hits"]]
                    == [h["sort"] for h in want["hits"]["hits"]]), missing

    def test_non_f32_exact_sort_falls_back(self, pair):
        mesh_idx, _ = pair
        # a fresh float column with non-f32-exact values via a new index
        from elasticsearch_tpu.common.settings import Settings
        from elasticsearch_tpu.index.index_service import IndexService

        idx = IndexService("meshinexact", Settings({
            "index.number_of_shards": 3,
            "index.search.mesh": True,
        }), mapping={"properties": {"t": {"type": "double"}}})
        for d in range(30):
            idx.index_doc(str(d), {"t": 1700000000000.0 + d})  # epoch ms
        idx.refresh()
        before = (idx._mesh_search.query_total
                  if idx._mesh_search is not None else 0)
        r = idx.search({"query": {"match_all": {}},
                        "sort": [{"t": "asc"}], "size": 5})
        # host fallback must serve it correctly (exact f64 ordering)
        assert [h["sort"] for h in r["hits"]["hits"]] == [
            [1700000000000.0 + d] for d in range(5)]
        after = (idx._mesh_search.query_total
                 if idx._mesh_search is not None else 0)
        assert after == before  # mesh path declined
        idx.close()


class TestMeshFeatureParity:
    """VERDICT r4 item 1: the mesh program must cover the collector-chain
    features (QueryPhase.java:179-268) — post_filter / min_score /
    terminate_after as mask stages, search_after as an oriented-key cut,
    rescore as an in-program window pass, slice as a deterministic doc
    partition, keyword sorts via global ordinals. Every test asserts
    mesh-vs-host parity AND that the mesh actually served the query."""

    BODY = TestIndexMeshAggsSort.BODY

    def _mk(self, name, mesh, n_docs=80, shards=3):
        from elasticsearch_tpu.common.settings import Settings
        from elasticsearch_tpu.index.index_service import IndexService

        idx = IndexService(name, Settings({
            "index.number_of_shards": shards,
            "index.search.mesh": mesh,
            "index.refresh_interval": -1,  # see TestIndexMeshAggsSort._mk
        }), mapping=self.BODY["mappings"])
        rng = np.random.RandomState(23)
        vocab = [f"w{i}" for i in range(10)]
        tags = ["amber", "blue", "coral", "denim", "ecru"]
        for d in range(n_docs):
            doc = {
                "body": " ".join(vocab[rng.randint(len(vocab))]
                                 for _ in range(6)),
                "price": d * 0.25,  # unique + f32-exact
            }
            if d % 9 != 0:  # keyword-missing docs for sort fills
                doc["tag"] = tags[rng.randint(len(tags))]
            if d % 7 != 0:
                doc["n"] = int(rng.randint(0, 40))
            idx.index_doc(str(d), doc)
        idx.refresh()
        return idx

    @pytest.fixture()
    def pair(self):
        mesh_idx = self._mk("meshfeat", True)
        host_idx = self._mk("hostfeat", False)
        yield mesh_idx, host_idx
        mesh_idx.close()
        host_idx.close()

    def _both(self, pair, body, mesh_used=True):
        mesh_idx, host_idx = pair
        before = (mesh_idx._mesh_search.query_total
                  if mesh_idx._mesh_search is not None else 0)
        got = mesh_idx.search(dict(body))
        want = host_idx.search(dict(body))
        after = mesh_idx._mesh_search.query_total
        if mesh_used:
            assert after == before + 1, "mesh path did not serve the query"
        else:
            assert after == before, "mesh path unexpectedly served it"
        return got, want

    @staticmethod
    def _same_hits(got, want, check_scores=True):
        assert got["hits"]["total"] == want["hits"]["total"]
        assert ([h["_id"] for h in got["hits"]["hits"]]
                == [h["_id"] for h in want["hits"]["hits"]])
        if check_scores:
            g = [h.get("_score") for h in got["hits"]["hits"]]
            w = [h.get("_score") for h in want["hits"]["hits"]]
            for a, b in zip(g, w):
                if a is None or b is None:
                    assert a == b
                else:
                    assert abs(a - b) < 1e-5, (g, w)

    def test_post_filter(self, pair):
        body = {
            "query": {"match": {"body": "w1 w4"}},
            "post_filter": {"term": {"tag": "blue"}},
            "size": 10,
            "aggs": {"tags": {"terms": {"field": "tag"}}},
        }
        got, want = self._both(pair, body)
        self._same_hits(got, want)
        # aggregations must see PRE-post_filter docs (the defining
        # property of post_filter)
        assert got["aggregations"] == want["aggregations"]
        assert len(got["aggregations"]["tags"]["buckets"]) > 1

    def test_min_score(self, pair):
        probe = pair[1].search({"query": {"match": {"body": "w1 w4"}},
                                "size": 1})
        cut = probe["hits"]["max_score"] * 0.6
        body = {
            "query": {"match": {"body": "w1 w4"}},
            "min_score": float(np.float32(cut)),
            "size": 10,
            "aggs": {"tags": {"terms": {"field": "tag"}}},
        }
        got, want = self._both(pair, body)
        self._same_hits(got, want)
        # min_score filters aggregations too (MinimumScoreCollector wraps
        # the whole chain)
        assert got["aggregations"] == want["aggregations"]

    def test_terminate_after(self, pair):
        body = {
            "query": {"match": {"body": "w2"}},
            "terminate_after": 3,
            "size": 5,
        }
        got, want = self._both(pair, body)
        # the cap is per shard (3 shards x 3): totals must agree
        assert got["hits"]["total"] == want["hits"]["total"]
        assert got["terminated_early"] is True
        assert want["terminated_early"] is True

    def test_search_after_numeric_sort(self, pair):
        base = {"query": {"match_all": {}},
                "sort": [{"price": {"order": "desc"}}], "size": 10}
        got1, want1 = self._both(pair, base)
        self._same_hits(got1, want1, check_scores=False)
        cursor = got1["hits"]["hits"][-1]["sort"]
        page2 = dict(base, search_after=cursor)
        got2, want2 = self._both(pair, page2)
        self._same_hits(got2, want2, check_scores=False)
        # pagination is gap-free and non-overlapping
        ids1 = {h["_id"] for h in got1["hits"]["hits"]}
        ids2 = {h["_id"] for h in got2["hits"]["hits"]}
        assert not ids1 & ids2
        # total is NOT affected by search_after (collector counts all)
        assert got2["hits"]["total"] == got1["hits"]["total"]

    def test_search_after_relevance(self, pair):
        base = {"query": {"match": {"body": "w3 w5"}}, "size": 5}
        got1, want1 = self._both(pair, base)
        cursor = [got1["hits"]["hits"][-1]["_score"]]
        page2 = dict(base, search_after=cursor)
        got2, want2 = self._both(pair, page2)
        self._same_hits(got2, want2)

    def test_keyword_sort_global_ordinals(self, pair):
        for order in ("asc", "desc"):
            body = {
                "query": {"match_all": {}},
                "sort": [{"tag": {"order": order}}],
                "size": 30,
            }
            got, want = self._both(pair, body)
            assert ([h["sort"] for h in got["hits"]["hits"]]
                    == [h["sort"] for h in want["hits"]["hits"]]), order
            # real terms surface as strings, missing docs as null
            vals = [h["sort"][0] for h in got["hits"]["hits"]]
            assert any(isinstance(v, str) for v in vals)

    def test_keyword_sort_search_after(self, pair):
        base = {"query": {"match_all": {}},
                "sort": [{"tag": {"order": "asc"}}], "size": 12}
        got1, want1 = self._both(pair, base)
        cursor = got1["hits"]["hits"][-1]["sort"]
        page2 = dict(base, search_after=cursor)
        got2, want2 = self._both(pair, page2)
        assert ([h["sort"] for h in got2["hits"]["hits"]]
                == [h["sort"] for h in want2["hits"]["hits"]])

    @pytest.mark.parametrize("mode", ["total", "multiply", "avg", "max",
                                      "min"])
    def test_rescore_modes(self, pair, mode):
        body = {
            "query": {"match": {"body": "w1"}},
            "rescore": {
                "window_size": 6,
                "query": {
                    "rescore_query": {"match": {"body": "w4"}},
                    "query_weight": 0.7,
                    "rescore_query_weight": 1.3,
                    "score_mode": mode,
                },
            },
            "size": 8,
        }
        got, want = self._both(pair, body)
        self._same_hits(got, want)

    def test_slice_partition(self, pair):
        mesh_idx, host_idx = pair
        all_ids = set()
        for i in range(3):
            body = {"query": {"match_all": {}},
                    "slice": {"id": i, "max": 3}, "size": 80}
            got, want = self._both(pair, body)
            self._same_hits(got, want, check_scores=False)
            ids = {h["_id"] for h in got["hits"]["hits"]}
            assert not ids & all_ids  # disjoint partitions
            all_ids |= ids
        assert len(all_ids) == 80  # exhaustive

    def test_suggest_and_highlight_on_mesh(self, pair):
        body = {
            "query": {"match": {"body": "w1"}},
            "size": 3,
            "highlight": {"fields": {"body": {}}},
            "suggest": {"s1": {"text": "w1", "term": {"field": "body"}}},
        }
        got, want = self._both(pair, body)
        self._same_hits(got, want)
        assert got.get("suggest") == want.get("suggest")
        assert ([h.get("highlight") for h in got["hits"]["hits"]]
                == [h.get("highlight") for h in want["hits"]["hits"]])

    def test_combined_feature_stack(self, pair):
        """Everything at once: the fused mask stages must compose."""
        body = {
            "query": {"match": {"body": "w1 w2 w3"}},
            "post_filter": {"range": {"n": {"gte": 5}}},
            "min_score": float(np.float32(0.05)),
            "size": 12,
            "aggs": {"tags": {"terms": {"field": "tag"}}},
        }
        got, want = self._both(pair, body)
        self._same_hits(got, want)
        assert got["aggregations"] == want["aggregations"]

    def test_collapse_still_falls_back(self, pair):
        body = {"query": {"match": {"body": "w1"}}, "size": 5,
                "collapse": {"field": "tag"}}
        got, want = self._both(pair, body, mesh_used=False)
        assert ([h["_id"] for h in got["hits"]["hits"]]
                == [h["_id"] for h in want["hits"]["hits"]])

    def test_profile_is_plane_truthful(self, pair):
        """ISSUE 8: "profile": true no longer demotes to the host path —
        the mesh serves it (mesh_used asserted by _both) and the profile
        section reports the serving plane + its phase spans, with hits
        identical to the unprofiled run."""
        base = {"query": {"match": {"body": "w1"}}, "size": 5}
        plain, _ = self._both(pair, dict(base))
        got, want = self._both(pair, dict(base, profile=True))
        assert ([h["_id"] for h in got["hits"]["hits"]]
                == [h["_id"] for h in plain["hits"]["hits"]])
        assert ([h["_score"] for h in got["hits"]["hits"]]
                == [h["_score"] for h in plain["hits"]["hits"]])
        prof = got["profile"]
        assert prof["plane"] == got["_plane"] != "host"
        assert {s["phase"] for s in prof["phases"]} >= {"kernel", "merge"}

    def test_rare_term_stays_on_mesh(self, pair):
        """A term present in only ONE shard's dictionary must not force
        the whole query off the mesh: absent shards plan an
        all-invalid-lane scorer with the same tree skeleton instead of
        MatchNone (PlanStructureMismatch -> silent host fallback)."""
        mesh_idx, host_idx = pair
        for idx in (mesh_idx, host_idx):
            idx.index_doc("rare", {"body": "zzz_unique_token"})
            idx.refresh()
        body = {"query": {"match": {"body": "zzz_unique_token"}}, "size": 5}
        got, want = self._both(pair, body)
        self._same_hits(got, want)
        assert got["hits"]["total"] == 1
        assert got["hits"]["hits"][0]["_id"] == "rare"

    def test_terminate_after_multi_segment_shards(self, pair):
        """terminate_after caps per SHARD; a mesh device holds one
        SEGMENT. With two segments per shard the per-device counts must
        be grouped by shard before capping, or mesh totals diverge from
        the host path (review finding, round 5)."""
        mesh_idx, host_idx = pair
        for idx in (mesh_idx, host_idx):  # second refresh -> 2nd segment
            for d in range(100, 130):
                idx.index_doc(str(d), {"body": "w2 w2 w2",
                                       "n": d, "price": d * 1.0})
            idx.refresh()
        body = {"query": {"match": {"body": "w2"}},
                "terminate_after": 4, "size": 5}
        got, want = self._both(pair, body)
        assert got["hits"]["total"] == want["hits"]["total"]
        assert got["terminated_early"] == want["terminated_early"] is True


class TestMeshPallasPlane:
    """The tentpole contract: the Pallas tile kernel IS the mesh
    program's scorer (one fast plane for distributed queries). Asserts
    mesh-vs-host parity for scores / top-k order / aggregations with the
    kernel serving (``_plane == "mesh_pallas"``, no silent fallback),
    including the PACKED case (segments > devices via slot unroll)."""

    MAPPING = {"properties": {
        "body": {"type": "text", "analyzer": "whitespace"},
        "tag": {"type": "keyword"},
        "n": {"type": "integer"},
        "price": {"type": "float"},
    }}

    @pytest.fixture(autouse=True)
    def _kernel_plans(self, monkeypatch):
        monkeypatch.setenv("ES_TPU_PALLAS", "interpret")

    def _mk(self, name, mesh, shards=3, batches=((0, 60),)):
        from elasticsearch_tpu.common.settings import Settings
        from elasticsearch_tpu.index.index_service import IndexService

        idx = IndexService(name, Settings({
            "index.number_of_shards": shards,
            "index.search.mesh": mesh,
            "index.refresh_interval": -1,
        }), mapping=self.MAPPING)
        rng = np.random.RandomState(17)
        vocab = [f"w{i}" for i in range(10)]
        tags = ["amber", "blue", "coral"]
        for lo, hi in batches:
            for d in range(lo, hi):
                doc = {"body": " ".join(vocab[rng.randint(len(vocab))]
                                        for _ in range(6)),
                       "tag": tags[d % 3], "price": d * 0.5}
                if d % 7 != 0:
                    doc["n"] = int(rng.randint(0, 40))
                idx.index_doc(str(d), doc)
            idx.refresh()  # each batch seals one segment per shard
        return idx

    @pytest.fixture()
    def pair(self):
        mesh_idx = self._mk("meshpal", True)
        host_idx = self._mk("hostpal", False)
        yield mesh_idx, host_idx
        mesh_idx.close()
        host_idx.close()

    @pytest.fixture()
    def packed_pair(self):
        # 5 shards x 2 sealed segments = 10 (shard, segment) pairs on the
        # 8-device mesh: the packed regime (slots_per_dev == 2)
        mesh_idx = self._mk("meshpalpk", True, shards=5,
                            batches=((0, 50), (100, 140)))
        host_idx = self._mk("hostpalpk", False, shards=5,
                            batches=((0, 50), (100, 140)))
        yield mesh_idx, host_idx
        mesh_idx.close()
        host_idx.close()

    @staticmethod
    def _check(mesh_idx, host_idx, body, plane="mesh_pallas"):
        before = (mesh_idx._mesh_search.pallas_query_total
                  if mesh_idx._mesh_search is not None else 0)
        got = mesh_idx.search(dict(body))
        want = host_idx.search(dict(body))
        assert got["_plane"] == plane, (got["_plane"], body)
        if plane == "mesh_pallas":
            assert (mesh_idx._mesh_search.pallas_query_total
                    == before + 1), "kernel plane did not serve the query"
        assert got["hits"]["total"] == want["hits"]["total"], body
        # same score sequence; doc identity may permute within EXACT
        # ties (same contract as TestMeshPlanParity)
        gs = [h.get("_score") for h in got["hits"]["hits"]]
        ws = [h.get("_score") for h in want["hits"]["hits"]]
        assert len(gs) == len(ws), body
        for a, b in zip(gs, ws):
            if a is None or b is None:
                assert a == b, body
            else:
                assert abs(a - b) < 1e-5, (body, gs, ws)
        gids = [h["_id"] for h in got["hits"]["hits"]]
        wids = [h["_id"] for h in want["hits"]["hits"]]
        assert ({i for i, s in zip(gids, gs) if gs.count(s) == 1}
                == {i for i, s in zip(wids, ws) if ws.count(s) == 1}), body
        if "aggs" in body:
            assert got["aggregations"] == want["aggregations"], body
        return got, want

    def test_match_parity_on_kernel_plane(self, pair):
        self._check(*pair, {"query": {"match": {"body": "w1 w4"}},
                            "size": 10})

    def test_bool_with_filter_and_aggs(self, pair):
        self._check(*pair, {
            "query": {"bool": {"must": [{"match": {"body": "w2 w5"}}],
                               "filter": [{"range": {"n": {"gte": 5}}}]}},
            "size": 10,
            "aggs": {"tags": {"terms": {"field": "tag"},
                              "aggs": {"avg_n": {"avg": {"field": "n"}}}},
                     "price_stats": {"stats": {"field": "price"}}},
        })

    def test_rare_term_stays_on_kernel_plane(self, pair):
        mesh_idx, host_idx = pair
        # present on exactly one shard's dictionary: absent shards keep
        # the kernel node with an empty lane set (same skeleton)
        for idx in pair:
            idx.index_doc("rare", {"body": "zzz_rare_token w1"})
            idx.refresh()
        got, _ = self._check(mesh_idx, host_idx,
                             {"query": {"match": {"body": "zzz_rare_token"}},
                              "size": 5})
        assert got["hits"]["total"] == 1
        assert got["hits"]["hits"][0]["_id"] == "rare"

    def test_min_should_match_counts(self, pair):
        self._check(*pair, {
            "query": {"bool": {
                "should": [{"term": {"body": "w0"}},
                           {"term": {"body": "w3"}},
                           {"term": {"body": "w9"}}],
                "minimum_should_match": 2}},
            "size": 10})

    def test_match_all_uses_scatter_mesh(self, pair):
        # no terms node -> nothing for the kernel to score; the query
        # still runs on the mesh (scatter formulation)
        self._check(*pair, {"query": {"match_all": {}},
                            "sort": [{"price": "desc"}], "size": 8},
                    plane="mesh")

    def test_packed_segments_exceed_devices(self, packed_pair):
        mesh_idx, host_idx = packed_pair
        got, _ = self._check(mesh_idx, host_idx,
                             {"query": {"match": {"body": "w1 w4"}},
                              "size": 10,
                              "aggs": {"tags": {"terms": {"field": "tag"}}}})
        ms = mesh_idx._mesh_search
        ex = ms._executor
        assert len(ms._pairs) > ex.n_dev, "corpus must exceed device count"
        assert ex.slots_per_dev >= 2
        assert ex.n_slots == ex.slots_per_dev * ex.n_dev

    def test_packed_post_filter_terminate_after(self, packed_pair):
        mesh_idx, host_idx = packed_pair
        self._check(mesh_idx, host_idx,
                    {"query": {"match": {"body": "w3"}},
                     "post_filter": {"term": {"tag": "blue"}}, "size": 10})
        # terminate_after caps per SHARD while slots are SEGMENTS
        body = {"query": {"match": {"body": "w1"}},
                "terminate_after": 3, "size": 5}
        got = mesh_idx.search(dict(body))
        want = host_idx.search(dict(body))
        assert got["_plane"] == "mesh_pallas"
        assert got["hits"]["total"] == want["hits"]["total"]
        assert got["terminated_early"] == want["terminated_early"]

    def test_plane_override_scatter(self):
        from elasticsearch_tpu.common.settings import Settings
        from elasticsearch_tpu.index.index_service import IndexService

        idx = IndexService("meshpalovr", Settings({
            "index.number_of_shards": 3,
            "index.search.mesh": True,
            "index.search.mesh.plane": "scatter",
            "index.refresh_interval": -1,
        }), mapping=self.MAPPING)
        for d in range(30):
            idx.index_doc(str(d), {"body": f"w{d % 5} w1"})
        idx.refresh()
        r = idx.search({"query": {"match": {"body": "w1"}}, "size": 5})
        assert r["_plane"] == "mesh"  # override keeps the kernel out
        assert idx._mesh_search.pallas_query_total == 0
        idx.close()

    def test_packing_limit_falls_back_to_host(self):
        from elasticsearch_tpu.common.settings import Settings
        from elasticsearch_tpu.index.index_service import IndexService

        idx = IndexService("meshpallim", Settings({
            "index.number_of_shards": 5,
            "index.search.mesh": True,
            "index.search.mesh.max_slots_per_device": 1,
            "index.refresh_interval": -1,
        }), mapping=self.MAPPING)
        for batch in range(2):
            for d in range(batch * 40, batch * 40 + 40):
                idx.index_doc(str(d), {"body": f"w{d % 5} w1"})
            idx.refresh()  # 10 segments > 8 devices * 1 slot
        r = idx.search({"query": {"match": {"body": "w1"}}, "size": 5})
        assert r["_plane"] == "host"
        idx.close()


class TestExecutionPlaneObservability:
    """VERDICT r4 weak 3: 'did we use the chip?' must be observable —
    plane markers on responses/profiles + counters in _stats."""

    def test_plane_markers_and_counters(self):
        from elasticsearch_tpu.common.settings import Settings
        from elasticsearch_tpu.index.index_service import IndexService

        idx = IndexService("obs", Settings({
            "index.number_of_shards": 3,
            "index.search.mesh": True,
        }), mapping={"properties": {"body": {"type": "text",
                                             "analyzer": "whitespace"}}})
        for d in range(30):
            idx.index_doc(str(d), {"body": f"w{d % 5} w1"})
        idx.refresh()
        # mesh-eligible query
        r1 = idx.search({"query": {"match": {"body": "w1"}}, "size": 5})
        assert r1["_plane"] == "mesh"
        # host-only query (collapse is mesh-UNSUPPORTED; profile no
        # longer demotes — ISSUE 8 plane-truthfulness); a profiled host
        # query still carries the per-segment tree
        r2 = idx._search_uncached(
            {"query": {"match": {"body": "w1"}}, "size": 5,
             "profile": True}, skip_mesh=True)
        assert r2["_plane"] == "host"
        shard_profile = r2["profile"]["shards"][0]
        assert shard_profile["plane"] == "host"
        assert shard_profile["searches"][0]["query"][0]["engine"] in (
            "pallas_tile_kernel", "xla_scatter")
        planes = idx.stats()["_all"]["total"]["search"]["planes"] \
            if "_all" in idx.stats() else \
            idx.stats()["total"]["search"]["planes"]
        assert planes["mesh_query_total"] >= 1
        assert planes["host_query_total"] >= 1
        assert (planes["pallas_segments_total"]
                + planes["scatter_segments_total"]) >= 1
        idx.close()
