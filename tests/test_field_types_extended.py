"""Extended field types: range family, token_count, binary, murmur3.

Ref: index/mapper/RangeFieldMapper.java (relations intersects/contains/
within), TokenCountFieldMapper, BinaryFieldMapper, plugins/mapper-murmur3.
"""

import pytest

from elasticsearch_tpu.common.errors import MapperParsingException
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService


def hit_ids(resp):
    return sorted(h["_id"] for h in resp["hits"]["hits"])


@pytest.fixture()
def ranges_idx():
    idx = IndexService("ranges", Settings({"index.number_of_shards": 1}))
    idx.put_mapping({
        "properties": {
            "age_range": {"type": "integer_range"},
            "temp": {"type": "float_range"},
            "window": {"type": "date_range"},
            "net": {"type": "ip_range"},
        }
    })
    idx.index_doc("1", {"age_range": {"gte": 10, "lte": 20}})
    idx.index_doc("2", {"age_range": {"gt": 20, "lt": 30}})  # (20,30) -> [21,29]
    idx.index_doc("3", {"age_range": {"gte": 5, "lte": 50}})
    idx.index_doc("4", {"temp": {"gte": 1.5, "lte": 2.5}})
    idx.index_doc("5", {"window": {"gte": "2017-01-01", "lte": "2017-06-30"}})
    idx.index_doc("6", {"net": "10.0.0.0/8"})
    idx.refresh()
    yield idx
    idx.close()


class TestRangeFields:
    def test_term_point_containment(self, ranges_idx):
        resp = ranges_idx.search({"query": {"term": {"age_range": 15}}})
        assert hit_ids(resp) == ["1", "3"]
        resp = ranges_idx.search({"query": {"term": {"age_range": 25}}})
        assert hit_ids(resp) == ["2", "3"]

    def test_exclusive_bounds(self, ranges_idx):
        # doc 2 is (20,30): 20 itself excluded
        resp = ranges_idx.search({"query": {"term": {"age_range": 20}}})
        assert hit_ids(resp) == ["1", "3"]

    def test_range_intersects_default(self, ranges_idx):
        resp = ranges_idx.search(
            {"query": {"range": {"age_range": {"gte": 18, "lte": 22}}}})
        assert hit_ids(resp) == ["1", "2", "3"]

    def test_range_within(self, ranges_idx):
        resp = ranges_idx.search({"query": {"range": {
            "age_range": {"gte": 9, "lte": 35, "relation": "within"}}}})
        assert hit_ids(resp) == ["1", "2"]

    def test_range_contains(self, ranges_idx):
        resp = ranges_idx.search({"query": {"range": {
            "age_range": {"gte": 12, "lte": 18, "relation": "contains"}}}})
        assert hit_ids(resp) == ["1", "3"]

    def test_float_range(self, ranges_idx):
        resp = ranges_idx.search({"query": {"term": {"temp": 2.0}}})
        assert hit_ids(resp) == ["4"]
        resp = ranges_idx.search({"query": {"term": {"temp": 3.0}}})
        assert hit_ids(resp) == []

    def test_date_range(self, ranges_idx):
        resp = ranges_idx.search({"query": {"term": {"window": "2017-03-01"}}})
        assert hit_ids(resp) == ["5"]
        resp = ranges_idx.search({"query": {"range": {
            "window": {"gte": "2017-06-01", "lte": "2017-12-31"}}}})
        assert hit_ids(resp) == ["5"]

    def test_ip_range_cidr(self, ranges_idx):
        resp = ranges_idx.search({"query": {"term": {"net": "10.1.2.3"}}})
        assert hit_ids(resp) == ["6"]
        resp = ranges_idx.search({"query": {"term": {"net": "11.0.0.1"}}})
        assert hit_ids(resp) == []

    def test_exists_on_range(self, ranges_idx):
        resp = ranges_idx.search({"query": {"exists": {"field": "age_range"}}})
        assert hit_ids(resp) == ["1", "2", "3"]

    def test_malformed_range_rejected(self, ranges_idx):
        with pytest.raises(MapperParsingException):
            ranges_idx.index_doc("x", {"age_range": {"bogus": 1}})
        with pytest.raises(MapperParsingException):
            ranges_idx.index_doc("y", {"age_range": 17})


class TestTokenCount:
    def test_token_count_subfield(self):
        idx = IndexService("tc", Settings({"index.number_of_shards": 1}))
        idx.put_mapping({"properties": {"name": {
            "type": "text",
            "fields": {"length": {"type": "token_count", "analyzer": "standard"}},
        }}})
        idx.index_doc("1", {"name": "John Smith"})
        idx.index_doc("2", {"name": "Rachel Alice Williams"})
        idx.refresh()
        resp = idx.search({"query": {"term": {"name.length": 3}}})
        assert hit_ids(resp) == ["2"]
        resp = idx.search({"query": {"range": {"name.length": {"lte": 2}}}})
        assert hit_ids(resp) == ["1"]
        idx.close()


class TestBinary:
    def test_binary_stored_not_searchable(self):
        idx = IndexService("bin", Settings({"index.number_of_shards": 1}))
        idx.put_mapping({"properties": {"blob": {"type": "binary"}}})
        idx.index_doc("1", {"blob": "U29tZSBiaW5hcnkgYmxvYg=="})
        idx.refresh()
        resp = idx.search({"query": {"match_all": {}}})
        assert resp["hits"]["hits"][0]["_source"]["blob"] == "U29tZSBiaW5hcnkgYmxvYg=="
        idx.close()

    def test_binary_invalid_base64(self):
        idx = IndexService("bin2", Settings({"index.number_of_shards": 1}))
        idx.put_mapping({"properties": {"blob": {"type": "binary", "doc_values": True}}})
        with pytest.raises(MapperParsingException):
            idx.index_doc("1", {"blob": "not!!base64&&"})
        idx.close()


class TestMurmur3:
    def test_murmur3_cardinality(self):
        idx = IndexService("m3", Settings({"index.number_of_shards": 1}))
        idx.put_mapping({"properties": {"tag": {
            "type": "keyword",
            "fields": {"hash": {"type": "murmur3"}},
        }}})
        for i, tag in enumerate(["a", "b", "a", "c", "b", "a"]):
            idx.index_doc(str(i), {"tag": tag})
        idx.refresh()
        resp = idx.search({
            "size": 0,
            "aggs": {"distinct": {"cardinality": {"field": "tag.hash"}}},
        })
        assert resp["aggregations"]["distinct"]["value"] == 3
        idx.close()
