"""REST API conformance tests, modeled on the reference's YAML REST suites
(rest-api-spec/src/main/resources/rest-api-spec/test/): do -> match steps
against a live node, here through the in-process client (wire-identical
request/response shapes)."""

import json

import pytest

from elasticsearch_tpu.client import Client
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.node import Node


@pytest.fixture()
def client():
    node = Node(Settings({"cluster.name": "test-cluster"}))
    c = Client(node)
    yield c
    node.close()


def ok(resp):
    status, payload = resp
    assert status in (200, 201), payload
    return payload


class TestRoot:
    def test_root(self, client):
        r = ok(client.perform("GET", "/"))
        assert r["cluster_name"] == "test-cluster"
        assert "version" in r and "tagline" in r

    def test_unknown_route(self, client):
        status, payload = client.perform("GET", "/_bogus_endpoint")
        assert status == 400
        assert "no handler found" in str(payload)


class TestDocumentCrud:
    def test_index_get_delete(self, client):
        status, r = client.index("idx", "1", {"title": "hello"})
        assert status == 201 and r["result"] == "created"
        assert r["_seq_no"] == 0 and r["_version"] == 1
        r = ok(client.get("idx", "1"))
        assert r["found"] and r["_source"] == {"title": "hello"}
        status, r = client.index("idx", "1", {"title": "hello2"})
        assert status == 200 and r["result"] == "updated" and r["_version"] == 2
        status, r = client.delete("idx", "1")
        assert status == 200 and r["result"] == "deleted"
        status, r = client.get("idx", "1")
        assert status == 404 and not r["found"]

    def test_auto_id(self, client):
        status, r = client.index("idx", None, {"a": 1})
        assert status == 201
        assert len(r["_id"]) >= 10

    def test_op_type_create_conflict(self, client):
        client.index("idx", "1", {"a": 1})
        status, r = client.perform("PUT", "/idx/_doc/1", {"op_type": "create"}, {"a": 2})
        assert status == 409

    def test_get_source(self, client):
        client.index("idx", "1", {"a": 1})
        r = ok(client.perform("GET", "/idx/_source/1"))
        assert r == {"a": 1}

    def test_update(self, client):
        client.index("idx", "1", {"a": 1, "b": 2})
        r = ok(client.update("idx", "1", {"doc": {"b": 3}}))
        assert r["_version"] == 2
        assert ok(client.get("idx", "1"))["_source"] == {"a": 1, "b": 3}

    def test_update_missing_doc_404(self, client):
        client.index("idx", "1", {"a": 1})
        status, r = client.update("idx", "missing", {"doc": {"b": 3}})
        assert status == 404

    def test_mget(self, client):
        client.index("idx", "1", {"a": 1})
        client.index("idx", "2", {"a": 2})
        r = ok(client.perform("POST", "/_mget", body={"docs": [
            {"_index": "idx", "_id": "1"},
            {"_index": "idx", "_id": "404"},
        ]}))
        assert r["docs"][0]["found"] and not r["docs"][1]["found"]

    def test_typed_route_compat(self, client):
        status, r = client.perform("PUT", "/idx/doc/1", body={"a": 1})
        assert status == 201
        status, r = client.perform("GET", "/idx/doc/1")
        assert status == 200 and r["found"]


class TestBulk:
    def test_bulk_ndjson(self, client):
        lines = "\n".join([
            json.dumps({"index": {"_index": "idx", "_id": "1"}}),
            json.dumps({"f": "one"}),
            json.dumps({"create": {"_index": "idx", "_id": "2"}}),
            json.dumps({"f": "two"}),
            json.dumps({"delete": {"_index": "idx", "_id": "404"}}),
            json.dumps({"update": {"_index": "idx", "_id": "1"}}),
            json.dumps({"doc": {"g": 9}}),
        ]) + "\n"
        r = ok(client.bulk(lines, refresh="true"))
        assert not r["errors"] or r["items"][2]["delete"]["status"] == 404
        assert r["items"][0]["index"]["status"] == 201
        assert r["items"][1]["create"]["status"] == 201
        assert r["items"][3]["update"]["status"] == 200
        status, sr = client.search("idx", {"query": {"match_all": {}}})
        assert sr["hits"]["total"] == 2

    def test_bulk_item_error_isolated(self, client):
        client.index("idx", "1", {"a": 1})
        lines = "\n".join([
            json.dumps({"create": {"_index": "idx", "_id": "1"}}),  # conflict
            json.dumps({"a": 2}),
            json.dumps({"index": {"_index": "idx", "_id": "2"}}),
            json.dumps({"a": 3}),
        ]) + "\n"
        r = ok(client.bulk(lines))
        assert r["errors"]
        assert r["items"][0]["create"]["status"] == 409
        assert r["items"][1]["index"]["status"] == 201


class TestSearchApi:
    def _seed(self, client):
        for i, color in enumerate(["red", "blue", "red", "green"]):
            client.index("things", str(i), {"color": color, "n": i,
                                            "text": f"item number {i}"})
        client.perform("POST", "/things/_refresh")

    def test_search_and_count(self, client):
        self._seed(client)
        r = ok(client.search("things", {"query": {"term": {"color": "red"}}}))
        assert r["hits"]["total"] == 2
        r = ok(client.count("things", {"query": {"term": {"color": "red"}}}))
        assert r["count"] == 2

    def test_uri_search(self, client):
        self._seed(client)
        status, r = client.perform("GET", "/things/_search",
                                   {"q": "color:red", "size": "1"})
        assert r["hits"]["total"] == 2 and len(r["hits"]["hits"]) == 1

    def test_msearch(self, client):
        self._seed(client)
        body = "\n".join([
            json.dumps({"index": "things"}),
            json.dumps({"query": {"term": {"color": "red"}}}),
            json.dumps({}),
            json.dumps({"query": {"match_all": {}}, "size": 0}),
        ]) + "\n"
        r = ok(client.perform("POST", "/_msearch", body=body))
        assert r["responses"][0]["hits"]["total"] == 2
        assert r["responses"][1]["hits"]["total"] == 4

    def test_scroll(self, client):
        self._seed(client)
        status, r1 = client.perform("POST", "/things/_search", {"scroll": "1m"},
                                    {"size": 2, "sort": [{"n": "asc"}],
                                     "query": {"match_all": {}}})
        sid = r1["_scroll_id"]
        ids1 = [h["_id"] for h in r1["hits"]["hits"]]
        status, r2 = client.perform("POST", "/_search/scroll", body={"scroll_id": sid})
        ids2 = [h["_id"] for h in r2["hits"]["hits"]]
        assert ids1 == ["0", "1"] and ids2 == ["2", "3"]
        status, r3 = client.perform("POST", "/_search/scroll", body={"scroll_id": sid})
        assert r3["hits"]["hits"] == []
        r = ok(client.perform("DELETE", "/_search/scroll", body={"scroll_id": sid}))
        assert r["num_freed"] == 1
        status, _ = client.perform("POST", "/_search/scroll", body={"scroll_id": sid})
        assert status == 404

    def test_validate_query(self, client):
        self._seed(client)
        r = ok(client.perform("POST", "/things/_validate/query",
                              body={"query": {"term": {"color": "red"}}}))
        assert r["valid"]
        r = ok(client.perform("POST", "/things/_validate/query",
                              body={"query": {"bogus": {}}}))
        assert not r["valid"]

    def test_field_caps(self, client):
        self._seed(client)
        r = ok(client.perform("GET", "/things/_field_caps", {"fields": "*"}))
        assert r["fields"]["n"]["long"]["aggregatable"]
        assert r["fields"]["text"]["text"]["searchable"]

    def test_explain(self, client):
        self._seed(client)
        r = ok(client.perform("GET", "/things/_explain/0",
                              body={"query": {"term": {"color": "red"}}}))
        assert r["matched"]
        r = ok(client.perform("GET", "/things/_explain/1",
                              body={"query": {"term": {"color": "red"}}}))
        assert not r["matched"]


class TestIndexAdmin:
    def test_create_with_mapping_and_settings(self, client):
        r = ok(client.perform("PUT", "/library", body={
            "settings": {"index": {"number_of_shards": 2}},
            "mappings": {"properties": {"title": {"type": "text"}}},
            "aliases": {"books": {}},
        }))
        assert r["acknowledged"]
        r = ok(client.perform("GET", "/library"))
        assert r["library"]["settings"]["index"]["number_of_shards"] == 2
        assert "title" in r["library"]["mappings"]["_doc"]["properties"]
        # search via alias
        client.index("books", "1", {"title": "via alias"})
        client.perform("POST", "/library/_refresh")
        status, sr = client.search("books", {"query": {"match": {"title": "alias"}}})
        assert sr["hits"]["total"] == 1

    def test_create_duplicate_fails(self, client):
        ok(client.perform("PUT", "/idx"))
        status, r = client.perform("PUT", "/idx")
        assert status == 400
        assert r["error"]["type"] == "index_already_exists_exception"

    def test_invalid_name(self, client):
        status, r = client.perform("PUT", "/_badname")
        assert status == 400

    def test_delete_index(self, client):
        ok(client.perform("PUT", "/idx"))
        ok(client.perform("DELETE", "/idx"))
        status, _ = client.perform("GET", "/idx")
        assert status == 404

    def test_exists_head(self, client):
        ok(client.perform("PUT", "/idx"))
        assert client.perform("HEAD", "/idx")[0] == 200
        assert client.perform("HEAD", "/nope")[0] == 404

    def test_open_close(self, client):
        client.index("idx", "1", {"a": 1})
        ok(client.perform("POST", "/idx/_close"))
        status, r = client.search("idx", {})
        # the reference answers with index_closed_exception (400)
        assert status == 400 and "closed" in r["error"]["reason"]
        ok(client.perform("POST", "/idx/_open"))
        client.perform("POST", "/idx/_refresh")
        status, r = client.search("idx", {})
        assert r["hits"]["total"] == 1

    def test_put_get_mapping(self, client):
        ok(client.perform("PUT", "/idx"))
        ok(client.perform("PUT", "/idx/_mapping",
                          body={"properties": {"age": {"type": "integer"}}}))
        r = ok(client.perform("GET", "/idx/_mapping"))
        assert r["idx"]["mappings"]["_doc"]["properties"]["age"]["type"] == "integer"

    def test_mapping_conflict_rejected(self, client):
        ok(client.perform("PUT", "/idx", body={
            "mappings": {"properties": {"age": {"type": "integer"}}}}))
        status, r = client.perform("PUT", "/idx/_mapping",
                                   body={"properties": {"age": {"type": "keyword"}}})
        assert status == 400

    def test_index_settings_dynamic_update(self, client):
        ok(client.perform("PUT", "/idx"))
        ok(client.perform("PUT", "/idx/_settings",
                          body={"index": {"refresh_interval": "30s"}}))
        r = ok(client.perform("GET", "/idx/_settings"))
        assert r["idx"]["settings"]["index"]["refresh_interval"] == "30s"
        status, _ = client.perform("PUT", "/idx/_settings",
                                   body={"index": {"number_of_shards": 9}})
        assert status == 400  # not dynamic

    def test_analyze(self, client):
        r = ok(client.perform("POST", "/_analyze",
                              body={"analyzer": "standard", "text": "Quick Fox!"}))
        assert [t["token"] for t in r["tokens"]] == ["quick", "fox"]

    def test_aliases_actions(self, client):
        ok(client.perform("PUT", "/idx1"))
        ok(client.perform("PUT", "/idx2"))
        ok(client.perform("POST", "/_aliases", body={"actions": [
            {"add": {"index": "idx1", "alias": "both"}},
            {"add": {"index": "idx2", "alias": "both"}},
        ]}))
        r = ok(client.perform("GET", "/_alias/both"))
        assert set(r) == {"idx1", "idx2"}
        ok(client.perform("POST", "/_aliases", body={"actions": [
            {"remove": {"index": "idx1", "alias": "both"}},
        ]}))
        r = ok(client.perform("GET", "/_alias/both"))
        assert set(r) == {"idx2"}

    def test_templates(self, client):
        ok(client.perform("PUT", "/_template/logs", body={
            "index_patterns": ["logs-*"],
            "settings": {"index": {"number_of_shards": 2}},
            "mappings": {"properties": {"@timestamp": {"type": "date"}}},
        }))
        client.index("logs-2017.01", "1", {"@timestamp": "2017-01-01", "msg": "x"})
        r = ok(client.perform("GET", "/logs-2017.01"))
        assert r["logs-2017.01"]["settings"]["index"]["number_of_shards"] == 2
        props = r["logs-2017.01"]["mappings"]["_doc"]["properties"]
        assert props["@timestamp"]["type"] == "date"
        assert client.perform("HEAD", "/_template/logs")[0] == 200
        ok(client.perform("DELETE", "/_template/logs"))
        assert client.perform("HEAD", "/_template/logs")[0] == 404

    def test_stats_and_segments(self, client):
        client.index("idx", "1", {"a": 1}, refresh="true")
        r = ok(client.perform("GET", "/idx/_stats"))
        assert r["indices"]["idx"]["total"]["docs"]["count"] == 1
        r = ok(client.perform("GET", "/idx/_segments"))
        assert r["indices"]["idx"]["shards"]

    def test_forcemerge(self, client):
        for i in range(3):
            client.index("idx", str(i), {"a": i}, refresh="true")
        ok(client.perform("POST", "/idx/_forcemerge"))
        r = ok(client.perform("GET", "/idx/_segments"))
        shards = r["indices"]["idx"]["shards"]
        # force-merge leaves at most ONE segment per shard (docs spread
        # over the default 5 shards, so shards-with-docs each show 1)
        assert all(len(s[0]["segments"]) <= 1 for s in shards.values())
        total_segs = sum(len(s[0]["segments"]) for s in shards.values())
        assert total_segs >= 1


class TestClusterApi:
    def test_health(self, client):
        client.index("idx", "1", {"a": 1})
        r = ok(client.perform("GET", "/_cluster/health"))
        assert r["status"] in ("green", "yellow")
        assert r["number_of_nodes"] == 1

    def test_health_green_with_zero_replicas(self, client):
        ok(client.perform("PUT", "/idx", body={
            "settings": {"index": {"number_of_replicas": 0}}}))
        r = ok(client.perform("GET", "/_cluster/health"))
        assert r["status"] == "green"

    def test_cluster_state_and_stats(self, client):
        client.index("idx", "1", {"a": 1})
        r = ok(client.perform("GET", "/_cluster/state"))
        assert "idx" in r["metadata"]["indices"]
        r = ok(client.perform("GET", "/_cluster/stats"))
        assert r["indices"]["count"] == 1

    def test_cluster_settings(self, client):
        r = ok(client.perform("PUT", "/_cluster/settings", body={
            "persistent": {"search.max_buckets": 1000}}))
        assert r["persistent"]["search"]["max_buckets"] == 1000
        r = ok(client.perform("GET", "/_cluster/settings"))
        assert r["persistent"]["search"]["max_buckets"] == 1000

    def test_nodes(self, client):
        r = ok(client.perform("GET", "/_nodes"))
        assert len(r["nodes"]) == 1
        r = ok(client.perform("GET", "/_nodes/stats"))
        assert len(r["nodes"]) == 1

    def test_scripts_crud(self, client):
        ok(client.perform("PUT", "/_scripts/myscript", body={
            "script": {"lang": "painless", "source": "params.x * 2"}}))
        r = ok(client.perform("GET", "/_scripts/myscript"))
        assert r["found"] and r["script"]["source"] == "params.x * 2"
        ok(client.perform("DELETE", "/_scripts/myscript"))
        assert client.perform("GET", "/_scripts/myscript")[0] == 404


class TestCatApi:
    def test_cat_indices_text_and_json(self, client):
        client.index("idx", "1", {"a": 1}, refresh="true")
        status, text = client.perform("GET", "/_cat/indices", {"v": ""})
        assert "idx" in text and "docs.count" in text
        status, rows = client.perform("GET", "/_cat/indices", {"format": "json"})
        assert rows[0]["index"] == "idx"
        assert rows[0]["docs.count"] == 1

    def test_cat_health_and_nodes(self, client):
        status, text = client.perform("GET", "/_cat/health")
        assert "green" in text or "yellow" in text
        status, text = client.perform("GET", "/_cat/nodes")
        assert "127.0.0.1" in text

    def test_cat_shards_count(self, client):
        client.index("idx", "1", {"a": 1}, refresh="true")
        status, text = client.perform("GET", "/_cat/shards")
        assert "idx" in text
        status, text = client.perform("GET", "/_cat/count")
        assert text.strip().endswith("1")


class TestIngestApi:
    def test_pipeline_crud_and_apply(self, client):
        ok(client.perform("PUT", "/_ingest/pipeline/p1", body={
            "processors": [
                {"set": {"field": "env", "value": "prod"}},
                {"uppercase": {"field": "code"}},
            ],
        }))
        r = ok(client.perform("GET", "/_ingest/pipeline/p1"))
        assert "p1" in r
        status, _ = client.perform("PUT", "/idx/_doc/1", {"pipeline": "p1"},
                                   {"code": "abc"})
        assert status == 201
        r = ok(client.get("idx", "1"))
        assert r["_source"] == {"code": "ABC", "env": "prod"}
        ok(client.perform("DELETE", "/_ingest/pipeline/p1"))
        assert client.perform("GET", "/_ingest/pipeline/p1")[0] == 404

    def test_simulate(self, client):
        r = ok(client.perform("POST", "/_ingest/pipeline/_simulate", body={
            "pipeline": {"processors": [{"rename": {
                "field": "a", "target_field": "b"}}]},
            "docs": [{"_source": {"a": 1}}],
        }))
        assert r["docs"][0]["doc"]["_source"] == {"b": 1}

    def test_grok(self, client):
        r = ok(client.perform("POST", "/_ingest/pipeline/_simulate", body={
            "pipeline": {"processors": [{"grok": {
                "field": "msg",
                "patterns": ["%{IP:client} %{WORD:method} %{NUMBER:bytes:int}"],
            }}]},
            "docs": [{"_source": {"msg": "10.0.0.1 GET 1234"}}],
        }))
        src = r["docs"][0]["doc"]["_source"]
        assert src["client"] == "10.0.0.1" and src["method"] == "GET"


class TestReindexApi:
    def test_reindex(self, client):
        for i in range(5):
            client.index("src", str(i), {"n": i}, refresh="true")
        r = ok(client.perform("POST", "/_reindex", body={
            "source": {"index": "src", "query": {"range": {"n": {"gte": 2}}}},
            "dest": {"index": "dst"},
        }))
        assert r["created"] == 3
        status, sr = client.search("dst", {})
        assert sr["hits"]["total"] == 3

    def test_delete_by_query(self, client):
        for i in range(5):
            client.index("idx", str(i), {"n": i}, refresh="true")
        r = ok(client.perform("POST", "/idx/_delete_by_query", body={
            "query": {"range": {"n": {"lt": 2}}}}))
        assert r["deleted"] == 2
        status, sr = client.search("idx", {})
        assert sr["hits"]["total"] == 3

    def test_update_by_query(self, client):
        for i in range(3):
            client.index("idx", str(i), {"n": i}, refresh="true")
        r = ok(client.perform("POST", "/idx/_update_by_query", body={}))
        assert r["updated"] == 3


class TestSnapshotApi:
    def test_snapshot_restore_cycle(self, client, tmp_path):
        for i in range(4):
            client.index("idx", str(i), {"n": i}, refresh="true")
        ok(client.perform("PUT", "/_snapshot/backup", body={
            "type": "fs", "settings": {"location": str(tmp_path / "repo")}}))
        r = ok(client.perform("PUT", "/_snapshot/backup/snap1", body={
            "indices": "idx"}))
        assert r["snapshot"]["state"] == "SUCCESS"
        r = ok(client.perform("GET", "/_snapshot/backup/snap1"))
        assert r["snapshots"][0]["indices"] == ["idx"]
        # restore under a new name
        r = ok(client.perform("POST", "/_snapshot/backup/snap1/_restore", body={
            "indices": "idx", "rename_pattern": "idx", "rename_replacement": "idx_restored",
        }))
        assert r["snapshot"]["indices"] == ["idx_restored"]
        status, sr = client.search("idx_restored", {})
        assert sr["hits"]["total"] == 4
        # cat + delete
        status, text = client.perform("GET", "/_cat/snapshots/backup")
        assert "snap1" in text
        ok(client.perform("DELETE", "/_snapshot/backup/snap1"))
        status, _ = client.perform("GET", "/_snapshot/backup/snap1")
        assert status == 404


class TestHttpServer:
    def test_live_http(self):
        import urllib.request

        from elasticsearch_tpu.rest.http_server import HttpServer

        node = Node(Settings({"cluster.name": "http-test"}))
        server = HttpServer(node, port=0)
        server.start()
        try:
            base = f"http://127.0.0.1:{server.port}"
            with urllib.request.urlopen(f"{base}/") as resp:
                root = json.loads(resp.read())
            assert root["cluster_name"] == "http-test"
            req = urllib.request.Request(
                f"{base}/idx/_doc/1?refresh=true", data=b'{"a": 1}',
                headers={"Content-Type": "application/json"}, method="PUT",
            )
            with urllib.request.urlopen(req) as resp:
                assert resp.status == 201
            req = urllib.request.Request(
                f"{base}/idx/_search", data=b'{"query": {"match_all": {}}}',
                headers={"Content-Type": "application/json"}, method="POST",
            )
            with urllib.request.urlopen(req) as resp:
                sr = json.loads(resp.read())
            assert sr["hits"]["total"] == 1
        finally:
            server.stop()
            node.close()
