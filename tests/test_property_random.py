"""Seeded randomized property tests — the ESTestCase strategy
(test/framework/.../ESTestCase.java: every run draws a seed, failures
print it, the seed reproduces the run bit-for-bit).

Random corpora + random query trees are checked against INVARIANTS and
a brute-force python oracle rather than hand-picked expectations:
- query hit set == the oracle's predicate evaluation, doc by doc
- search total == _count == len(oracle set)
- bool.filter vs bool.must produce the same hit SET (scores aside)
- sorted search_after pagination walks every hit exactly once
- a 3-shard index returns the same hit set as a 1-shard index
- terms agg counts == oracle value histogram

Seeds are fixed here for reproducibility; widen SEEDS locally for a
soak run (the reference's -Dtests.iters analog).
"""

import numpy as np
import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService

SEEDS = [7, 23, 1009]

WORDS = ["alpha", "beta", "gamma", "delta", "epsilon", "zeta", "eta",
         "theta", "iota", "kappa"]
TAGS = ["red", "green", "blue", "black", "white"]


def gen_corpus(rng, n_docs):
    docs = {}
    for i in range(n_docs):
        doc = {
            "body": " ".join(rng.choice(WORDS)
                             for _ in range(rng.randint(1, 8))),
            "tag": str(rng.choice(TAGS)),
            # unique tiebreak for search_after cursors: _doc is a
            # per-segment ordinal, NOT unique across shards (the
            # reference documents the same caveat)
            "uid": f"{i:04d}",
        }
        if rng.random() < 0.85:  # some docs miss the numeric field
            doc["n"] = int(rng.randint(0, 100))
        if rng.random() < 0.5:
            doc["price"] = round(float(rng.randint(0, 400)) * 0.25, 2)
        docs[str(i)] = doc
    return docs


def gen_query(rng, depth=0):
    """Random query tree + its oracle predicate over a source dict."""
    choices = ["term_body", "term_tag", "range_n", "exists", "match"]
    if depth < 2:
        choices += ["bool", "bool"]
    kind = rng.choice(choices)
    if kind == "term_body":
        w = rng.choice(WORDS)
        return ({"term": {"body": w}},
                lambda s, w=w: w in s["body"].split())
    if kind == "term_tag":
        t = rng.choice(TAGS)
        return ({"term": {"tag": t}}, lambda s, t=t: s["tag"] == t)
    if kind == "range_n":
        lo = int(rng.randint(0, 80))
        hi = lo + int(rng.randint(5, 40))
        return ({"range": {"n": {"gte": lo, "lt": hi}}},
                lambda s, lo=lo, hi=hi: "n" in s and lo <= s["n"] < hi)
    if kind == "exists":
        f = rng.choice(["n", "price"])
        return ({"exists": {"field": f}}, lambda s, f=f: f in s)
    if kind == "match":
        ws = [rng.choice(WORDS) for _ in range(rng.randint(1, 3))]
        return ({"match": {"body": " ".join(ws)}},
                lambda s, ws=tuple(ws): any(w in s["body"].split()
                                            for w in ws))
    # bool
    n_must = int(rng.randint(0, 2))
    n_should = int(rng.randint(0, 3))
    n_not = int(rng.randint(0, 2))
    musts = [gen_query(rng, depth + 1) for _ in range(n_must)]
    shoulds = [gen_query(rng, depth + 1) for _ in range(n_should)]
    nots = [gen_query(rng, depth + 1) for _ in range(n_not)]
    body = {"bool": {}}
    if musts:
        body["bool"]["must"] = [q for q, _ in musts]
    if shoulds:
        body["bool"]["should"] = [q for q, _ in shoulds]
    if nots:
        body["bool"]["must_not"] = [q for q, _ in nots]

    def pred(s, musts=musts, shoulds=shoulds, nots=nots):
        if any(not p(s) for _, p in musts):
            return False
        if any(p(s) for _, p in nots):
            return False
        if shoulds and not musts:
            return any(p(s) for _, p in shoulds)
        return True

    return body, pred


MAPPING = {"properties": {
    "body": {"type": "text", "analyzer": "whitespace"},
    "tag": {"type": "keyword"},
    "uid": {"type": "keyword"},
    "n": {"type": "integer"},
    "price": {"type": "float"},
}}


def build_index(name, docs, shards=1):
    idx = IndexService(name, Settings({"index.number_of_shards": shards}),
                       MAPPING)
    for i, src in docs.items():
        idx.index_doc(i, dict(src))
    idx.refresh()
    return idx


@pytest.mark.parametrize("seed", SEEDS)
class TestRandomizedProperties:
    def test_query_oracle_and_invariants(self, seed):
        rng = np.random.RandomState(seed)
        docs = gen_corpus(rng, 120)
        idx = build_index(f"prop{seed}", docs)
        idx3 = build_index(f"prop3x{seed}", docs, shards=3)
        try:
            for qi in range(15):
                q, pred = gen_query(rng)
                expect = {i for i, s in docs.items() if pred(s)}
                r = idx.search({"query": q, "size": len(docs)})
                got = {h["_id"] for h in r["hits"]["hits"]}
                assert got == expect, f"seed={seed} q#{qi} {q}"
                # total == hit set == count API (the r/count invariant)
                assert r["hits"]["total"] == len(expect), (seed, qi, q)
                c = idx.count({"query": q})
                assert c["count"] == len(expect), (seed, qi, q)
                # filter vs must: same SET
                rf = idx.search({"query": {"bool": {"filter": [q]}},
                                 "size": len(docs)})
                assert {h["_id"] for h in rf["hits"]["hits"]} == expect
                # shard-count independence
                r3 = idx3.search({"query": q, "size": len(docs)})
                assert {h["_id"] for h in r3["hits"]["hits"]} == expect, \
                    f"seed={seed} q#{qi} 3-shard diverged"
        finally:
            idx.close()
            idx3.close()

    def test_search_after_pagination_complete(self, seed):
        rng = np.random.RandomState(seed)
        docs = gen_corpus(rng, 90)
        idx = build_index(f"page{seed}", docs, shards=2)
        try:
            q, pred = gen_query(rng)
            expect = {i for i, s in docs.items() if pred(s)}
            seen = []
            after = None
            for _ in range(100):
                body = {"query": q, "size": 7,
                        "sort": [{"n": {"order": "asc", "missing": "_last"}},
                                 {"uid": "asc"}]}
                if after is not None:
                    body["search_after"] = after
                hits = idx.search(body)["hits"]["hits"]
                if not hits:
                    break
                seen.extend(h["_id"] for h in hits)
                after = hits[-1]["sort"]
            assert len(seen) == len(set(seen)), f"seed={seed} duplicate page hits"
            assert set(seen) == expect, f"seed={seed} pagination lost docs"
        finally:
            idx.close()

    def test_terms_agg_matches_histogram(self, seed):
        rng = np.random.RandomState(seed)
        docs = gen_corpus(rng, 150)
        idx = build_index(f"agg{seed}", docs, shards=2)
        try:
            q, pred = gen_query(rng)
            matched = [s for i, s in docs.items() if pred(s)]
            expect = {}
            for s in matched:
                expect[s["tag"]] = expect.get(s["tag"], 0) + 1
            r = idx.search({"query": q, "size": 0, "aggs": {
                "tags": {"terms": {"field": "tag",
                                   "size": len(TAGS)}}}})
            got = {b["key"]: b["doc_count"]
                   for b in r["aggregations"]["tags"]["buckets"]}
            assert got == expect, f"seed={seed} {q}"
        finally:
            idx.close()


@pytest.mark.parametrize("seed", SEEDS)
def test_keyword_sort_merges_by_string_across_shards(seed):
    """Regression (found BY this harness): per-segment ordinals must never
    be cross-segment merge keys — keyword sorts compare strings."""
    rng = np.random.RandomState(seed)
    docs = gen_corpus(rng, 80)
    idx = build_index(f"kws{seed}", docs, shards=3)
    try:
        r = idx.search({"query": {"match_all": {}},
                        "sort": [{"tag": "asc"}, {"uid": "asc"}],
                        "size": len(docs)})
        got = [h["_source"]["tag"] for h in r["hits"]["hits"]]
        assert got == sorted(got), f"seed={seed} keyword order broken"
        assert [h["sort"][0] for h in r["hits"]["hits"]] == got
        # keyword search_after pagination completes without loss/dupes
        seen, after = [], None
        for _ in range(60):
            body = {"query": {"match_all": {}}, "size": 9,
                    "sort": [{"tag": "asc"}, {"uid": "asc"}]}
            if after is not None:
                body["search_after"] = after
            hits = idx.search(body)["hits"]["hits"]
            if not hits:
                break
            seen.extend(h["_id"] for h in hits)
            after = hits[-1]["sort"]
        assert len(seen) == len(set(seen)) == len(docs)
    finally:
        idx.close()
