"""Zero-downtime rollout (ISSUE 14): graceful drain, synced-flush
warm restart, graceful cluster leave, persistent compile cache + AOT
variant warming, and the rolling-restart soak.

Contracts under test (docs/RESILIENCE.md "Rollout & drain"):
- a draining index stops admitting with a clean 503 + Retry-After and
  sheds its queue with exact counters (no silent drops); in-flight
  searches finish; undrain resumes service;
- Node.close() shuts admission down FIRST, drains in-flight searches,
  then flushes with a synced-flush marker and closes indices — queued
  work is never stranded;
- warm restart over a persistent data path is ops-free (zero translog
  ops replayed) and byte-identical;
- ClusterNode.close() announces a graceful leave (replicas promote on
  the leave publish, not the FD timeout) and deregisters from
  transport BEFORE closing shards;
- the variant registry + warming replay eliminate query-path first
  compiles after a warmed restart (compile_cache counters prove it).
"""

import threading
import time

import pytest

from elasticsearch_tpu.common.errors import NodeDrainingException
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService
from elasticsearch_tpu.testing.chaos import RollingRestartSoak


def _mk_index(name="drainidx", **settings):
    base = {"index.number_of_shards": 2, "index.refresh_interval": -1}
    base.update(settings)
    return IndexService(name, Settings(base), mapping={"properties": {
        "body": {"type": "text", "analyzer": "whitespace"}}})


class TestAdmissionDrain:
    def test_drain_rejects_new_and_sheds_queued_with_exact_counters(self):
        svc = _mk_index("drain1", **{
            "search.admission.max_concurrent": 1,
            "search.queue.size": 8})
        adm = svc.admission
        try:
            for d in range(6):
                svc.index_doc(str(d), {"body": f"w{d % 2} common"})
            svc.refresh()
            # occupy the single slot so followers queue
            hold = adm.acquire(tenant="holder")
            results = []

            def queued():
                try:
                    token = adm.acquire(tenant="queued")
                    adm.release(token)
                    results.append("admitted")
                except NodeDrainingException as e:
                    results.append(("draining", e.retry_after_s))
                except Exception as e:  # noqa: BLE001
                    results.append(type(e).__name__)

            t = threading.Thread(target=queued)
            t.start()
            for _ in range(200):
                if adm._queued_total:
                    break
                time.sleep(0.005)
            assert adm._queued_total == 1
            base = adm.stats_dict()
            shed = adm.begin_drain()
            t.join(5)
            # the queued entry was shed with the clean 503 + Retry-After
            assert shed == 1
            assert results and results[0][0] == "draining"
            assert results[0][1] > 0
            # new arrivals get the same contract (from a fresh thread —
            # the holder's own thread would take the nested-query bypass)
            late: list = []

            def late_arrival():
                try:
                    adm.acquire(tenant="late")
                    late.append("admitted")
                except NodeDrainingException:
                    late.append("draining")

            t2 = threading.Thread(target=late_arrival)
            t2.start()
            t2.join(5)
            assert late == ["draining"]
            stats = adm.stats_dict()
            assert stats["draining"] is True
            assert stats["drain_rejected_total"] == 2
            # the exact partition admitted+rejected+expired survives
            assert (stats["rejected_total"] - base["rejected_total"]) == 2
            # the in-flight holder finishes and the drain completes
            assert adm.await_drained(0.05) is False  # holder still in
            adm.release(hold)
            assert adm.await_drained(5) is True
            # undrain resumes service
            adm.end_drain()
            token = adm.acquire(tenant="resumed")
            adm.release(token)
            assert adm.stats_dict()["draining"] is False
        finally:
            svc.close()

    def test_draining_search_returns_503_with_retry_after(self):
        svc = _mk_index("drain2")
        try:
            for d in range(4):
                svc.index_doc(str(d), {"body": "w0 common"})
            svc.refresh()
            svc.admission.begin_drain()
            with pytest.raises(NodeDrainingException) as ei:
                svc.search({"query": {"match": {"body": "common"}}})
            assert ei.value.status_code == 503
            assert ei.value.retry_after_s > 0
            svc.admission.end_drain()
            r = svc.search({"query": {"match": {"body": "common"}}})
            assert r["hits"]["total"] == 4
        finally:
            svc.close()

    def test_drain_rejects_even_with_admission_disabled(self):
        # the kill switch (search.admission.enabled=false) must not
        # void the drain contract: new arrivals still get the clean 503
        svc = _mk_index("drain4", **{"search.admission.enabled": False})
        try:
            for d in range(4):
                svc.index_doc(str(d), {"body": "w0 common"})
            svc.refresh()
            r = svc.search({"query": {"match": {"body": "common"}}})
            assert r["hits"]["total"] == 4  # admitted via the bypass
            svc.admission.begin_drain()
            with pytest.raises(NodeDrainingException):
                svc.search({"query": {"match": {"body": "common"}}})
        finally:
            svc.close()

    def test_index_created_while_node_drains_joins_the_drain(self):
        from elasticsearch_tpu.node import Node

        node = Node(Settings.EMPTY)
        try:
            node.create_index("pre", {"settings": {
                "number_of_shards": 1, "index.refresh_interval": -1}})
            node.drain()
            # a straggling write auto-creates an index mid-drain: it
            # must refuse searches like every other index on the node
            node.index_doc("straggler", "1", {"f": 1})
            assert node.indices["straggler"].admission.draining
            with pytest.raises(NodeDrainingException):
                node.search("straggler", {"query": {"match_all": {}}})
            node.undrain()
        finally:
            node.close()

    def test_nested_queries_of_admitted_search_survive_drain(self):
        # an in-flight search's nested re-entry (collapse expansion,
        # hybrid sides) must not be rejected by a drain that began
        # after the outer query was admitted
        svc = _mk_index("drain3")
        try:
            for d in range(4):
                svc.index_doc(str(d), {"body": "w0 common"})
            svc.refresh()
            adm = svc.admission
            outer = adm.acquire(tenant="outer")
            adm.begin_drain()
            # the nested-guard contextvar is set by the outer token:
            # a nested acquire must return the noop token, not raise
            nested = adm.acquire(tenant="outer")
            assert nested.noop
            adm.release(nested)
            adm.release(outer)
            assert adm.await_drained(5) is True
        finally:
            svc.close()


class TestNodeDrainAndWarmRestart:
    def test_node_close_does_not_strand_inflight_search(self, tmp_path):
        from elasticsearch_tpu.node import Node
        from elasticsearch_tpu.testing import disruption as dis

        node = Node(Settings.EMPTY, data_path=str(tmp_path / "n1"))
        node.create_index("inflight", {"settings": {
            "index.number_of_shards": 2, "index.refresh_interval": -1}})
        for d in range(6):
            node.index_doc("inflight", str(d), {"body": "w0 common"})
        node.indices["inflight"].refresh()
        scheme = dis.SearchDelayScheme(0.05, indices=["inflight"]).install()
        out = {}

        def slow_search():
            try:
                out["resp"] = node.search(
                    "inflight", {"query": {"match": {"body": "common"}}})
            except Exception as e:  # noqa: BLE001
                out["error"] = e

        t = threading.Thread(target=slow_search)
        try:
            t.start()
            time.sleep(0.02)  # the search is admitted and executing
            node.close()  # drains first: the search must COMPLETE
            t.join(10)
            assert "error" not in out, out.get("error")
            assert out["resp"]["hits"]["total"] == 6
        finally:
            scheme.remove()

    def test_drained_restart_is_ops_free_and_byte_identical(self, tmp_path):
        from elasticsearch_tpu.cluster.multinode import (
            clear_recovery_progress,
            recovery_progress_rows,
        )
        from elasticsearch_tpu.node import Node

        clear_recovery_progress()
        path = str(tmp_path / "warm")
        node = Node(Settings.EMPTY, data_path=path)
        node.create_index("warmidx", {"settings": {
            "index.number_of_shards": 2, "index.refresh_interval": -1}})
        for d in range(10):
            node.index_doc("warmidx", str(d), {"body": f"w{d % 3} common"})
        node.indices["warmidx"].refresh()
        probe = {"query": {"match": {"body": "common"}}, "size": 10}
        want = [(h["_id"], h["_score"])
                for h in node.search("warmidx", dict(probe))["hits"]["hits"]]
        report = node.drain()
        assert report["drained"] is True
        # every shard carries the synced-flush marker + empty translog
        for shard in node.indices["warmidx"].shards.values():
            assert shard.engine.last_sync_id is not None
            assert shard.engine.translog.stats()[
                "uncommitted_operations"] == 0
        node.close()

        node2 = Node(Settings.EMPTY, data_path=path)
        try:
            rows = [r for r in recovery_progress_rows()
                    if r["index"] == "warmidx" and r["type"] == "store"]
            assert len(rows) == 2, rows
            assert all(r["ops_recovered"] == 0 for r in rows), rows
            got = [(h["_id"], h["_score"]) for h in
                   node2.search("warmidx", dict(probe))["hits"]["hits"]]
            assert got == want
            for shard in node2.indices["warmidx"].shards.values():
                assert shard.engine.last_sync_id is not None
        finally:
            node2.close()
            clear_recovery_progress()

    def test_undrain_via_rest_surface(self):
        from elasticsearch_tpu.client import Client
        from elasticsearch_tpu.node import Node

        node = Node(Settings.EMPTY)
        try:
            node.create_index("restdrain", {"settings": {
                "index.number_of_shards": 1,
                "index.refresh_interval": -1}})
            client = Client(node)
            status, body = client.perform("POST", "/_nodes/_local/_drain")
            assert status == 200 and body["draining"] is True
            assert node.indices["restdrain"].admission.draining
            status, body = client.perform("DELETE",
                                          "/_nodes/_local/_drain")
            assert status == 200 and body["draining"] is False
            assert not node.indices["restdrain"].admission.draining
        finally:
            node.close()


class TestGracefulLeave:
    def _cluster(self, names=("ga", "gb", "gc")):
        from elasticsearch_tpu.cluster.multinode import ClusterNode
        from elasticsearch_tpu.transport.local import TransportHub

        hub = TransportHub()
        nodes = {n: ClusterNode(n, hub) for n in names}
        nodes[names[0]].bootstrap_cluster()
        for n in names[1:]:
            nodes[n].join(names[0])
        return hub, nodes

    def test_follower_leave_removes_it_without_fd(self):
        hub, nodes = self._cluster()
        nodes["ga"].create_index("gidx", {
            "index": {"number_of_shards": 2, "number_of_replicas": 1}})
        nodes["gc"].close(graceful=True)
        # no fault-detection tick ran: the leave announcement alone
        # removed the node and rerouted its shards
        assert "gc" not in nodes["ga"].known_nodes
        for copies in nodes["ga"].routing["gidx"].values():
            assert all(c.node_id != "gc" for c in copies)
            assert any(c.primary for c in copies)
        nodes["ga"].close(graceful=False)
        nodes["gb"].close(graceful=False)

    def test_master_abdicates_and_replicas_promote_on_leave(self):
        from elasticsearch_tpu.cluster.state import ShardRoutingState

        hub, nodes = self._cluster()
        nodes["ga"].create_index("gidx2", {
            "index": {"number_of_shards": 2, "number_of_replicas": 1}})
        for _ in range(40):
            nodes["ga"].reroute()
            copies = [c for cs in nodes["ga"].routing["gidx2"].values()
                      for c in cs]
            if copies and all(c.state == ShardRoutingState.STARTED
                              for c in copies):
                break
            time.sleep(0.05)
        old_terms = dict(nodes["gb"].primary_terms)
        had_primary = {sid for sid, cs in nodes["ga"].routing["gidx2"]
                       .items() if any(c.primary and c.node_id == "ga"
                                       for c in cs)}
        nodes["ga"].close(graceful=True)
        # lowest-id surviving eligible node took over WITHOUT an epoch
        # of fault-detection silence
        assert nodes["gb"].is_master
        assert "ga" not in nodes["gb"].known_nodes
        for sid, copies in nodes["gb"].routing["gidx2"].items():
            primary = [c for c in copies if c.primary]
            assert primary and primary[0].node_id != "ga"
            if sid in had_primary:
                # the promotion bumped the fencing term
                assert nodes["gb"].primary_terms[("gidx2", sid)] \
                    > old_terms.get(("gidx2", sid), 1)
        nodes["gb"].close(graceful=False)
        nodes["gc"].close(graceful=False)

    def test_close_deregisters_transport_before_shard_close(self):
        from elasticsearch_tpu.common.errors import (
            NodeNotConnectedException,
        )
        from elasticsearch_tpu.cluster.multinode import ACTION_GET

        hub, nodes = self._cluster(names=("ha", "hb"))
        nodes["ha"].create_index("hidx", {
            "index": {"number_of_shards": 1, "number_of_replicas": 0}})
        nodes["hb"].close(graceful=True)
        # a routed request to the closed node fails FAST at the hub —
        # it can never reach a half-closed shard
        with pytest.raises(NodeNotConnectedException):
            nodes["ha"].transport.send_request(
                "hb", ACTION_GET, {"index": "hidx", "shard": 0,
                                   "id": "x"})
        nodes["ha"].close(graceful=False)


class TestCompileCachePlane:
    def test_variant_registry_round_trip(self, tmp_path):
        from elasticsearch_tpu.common import compile_cache as cc

        path = str(tmp_path / "variants.json")
        reg = cc.VariantRegistry(path)
        assert not reg.program_known("serial:abc")
        reg.record_program("serial:abc")
        reg.record_warm("idx", "k1", {"kind": "search",
                                      "bodies": [{"size": 1}]})
        # a fresh load (the next process) sees both — and the program
        # key now counts as preexisting (the cache-hit baseline)
        reg2 = cc.VariantRegistry(path)
        assert reg2.program_known("serial:abc")
        assert reg2.warm_entries("idx") == [
            {"kind": "search", "bodies": [{"size": 1}]}]
        reg2.forget_index("idx")
        assert cc.VariantRegistry(path).warm_entries("idx") == []

    def test_instrument_program_counts_first_call_once(self):
        from elasticsearch_tpu.common import compile_cache as cc

        calls = []
        fn = cc.instrument_program(lambda x: calls.append(x) or x,
                                   "serial", "serial:testkey1")
        before = cc.compile_stats().stats()
        assert fn(1) == 1 and fn(2) == 2
        after = cc.compile_stats().stats()
        first = (after["compile_cache_hit_total"]
                 + after["compile_cache_miss_total"]
                 - before["compile_cache_hit_total"]
                 - before["compile_cache_miss_total"])
        assert first == 1
        assert "serial:testkey1" in cc.variant_registry().programs

    def test_warming_context_classifies_first_call(self):
        from elasticsearch_tpu.common import compile_cache as cc

        before = cc.compile_stats().stats()
        fn = cc.instrument_program(lambda: None, "serial",
                                   "serial:testkey2")
        with cc.warming():
            fn()
        after = cc.compile_stats().stats()
        assert (after["programs_warmed_total"]
                - before["programs_warmed_total"]) == 1
        assert (after["query_path_first_compile_total"]
                == before["query_path_first_compile_total"])

    def test_compile_block_exported_in_stats(self):
        svc = _mk_index("compstats")
        try:
            for d in range(6):
                svc.index_doc(str(d), {"body": "w0 common"})
            svc.refresh()
            svc.search({"query": {"match": {"body": "common"}}})
            block = svc.search_stats()["compile"]
            for key in ("cache_enabled", "variants_recorded",
                        "compile_cache_hit_total",
                        "compile_cache_miss_total",
                        "programs_warmed_total",
                        "query_path_first_compile_total",
                        "first_compile_stall_ms",
                        "first_compile_events"):
                assert key in block, block.keys()
        finally:
            svc.close()

    def test_mesh_query_records_warmable_variant(self):
        from elasticsearch_tpu.common import compile_cache as cc

        cc.set_variant_registry(cc.VariantRegistry(None))
        svc = _mk_index("varrec", **{"index.search.mesh.plane": "pallas"})
        try:
            for d in range(8):
                svc.index_doc(str(d), {"body": f"w{d % 2} common"})
            svc.refresh()
            r = svc.search({"query": {"match": {"body": "common"}},
                            "size": 5})
            if r["_plane"] in ("mesh_pallas", "mesh"):
                entries = cc.variant_registry().warm_entries("varrec")
                assert entries, "mesh-served query recorded no variant"
                assert entries[0]["kind"] == "search"
                # warming replays it without growing the lattice
                n_before = len(cc.variant_registry().warm_entries("varrec"))
                assert svc.warm_compile_variants() >= 1
                assert len(cc.variant_registry()
                           .warm_entries("varrec")) == n_before
        finally:
            svc.close()
            cc.set_variant_registry(cc.VariantRegistry(None))


class TestRollingRestartSoak:
    @pytest.fixture(autouse=True)
    def _interpret(self, monkeypatch):
        monkeypatch.setenv("ES_TPU_PALLAS", "interpret")

    def test_smoke(self, tmp_path):
        soak = RollingRestartSoak(str(tmp_path / "soak"), seed=11,
                                  nodes=3, shards=2, seed_docs=16,
                                  docs_per_roll=4, searches_per_roll=4,
                                  drain_searches=3, index="rollsmoke")
        report = soak.run()
        assert report["drain"]["drain"]["drained"] is True
        assert report["drain"]["ops_replayed"] == 0
        assert report["drain"]["restart_hits_identical"] is True
        assert report["cluster"]["acked"] >= 16 + 3 * 4
        assert report["cluster"]["hits_identical"] is True
        assert len(report["cluster"]["rolls"]) == 3
        comp = report["compile"]
        assert comp["query_path_first_compiles"] == 0
        assert comp["programs_warmed"] >= 1
        assert comp["hits_identical"] and comp["ledger_restored"]


@pytest.mark.slow
class TestRollingRestartSoakFull:
    def test_full(self, tmp_path, monkeypatch):
        monkeypatch.setenv("ES_TPU_PALLAS", "interpret")
        soak = RollingRestartSoak(str(tmp_path / "soakfull"), seed=23,
                                  nodes=3, shards=3, seed_docs=60,
                                  docs_per_roll=20, searches_per_roll=12,
                                  drain_searches=6, index="rollfull")
        report = soak.run()
        assert report["cluster"]["hits_identical"] is True
        assert report["compile"]["query_path_first_compiles"] == 0
