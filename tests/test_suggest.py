"""Suggester tests (ref: search/suggest — term, phrase, completion)."""

import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService


@pytest.fixture()
def idx():
    svc = IndexService("s", Settings({"index.number_of_shards": 1}), {
        "properties": {
            "body": {"type": "text"},
            "suggest": {"type": "completion"},
        }
    })
    docs = [
        {"body": "the quick brown fox", "suggest": {"input": ["quick fox"], "weight": 10}},
        {"body": "quick silver lining", "suggest": {"input": ["quick silver", "silver"], "weight": 5}},
        {"body": "brown bears fishing", "suggest": "brown bears"},
        {"body": "the quick brown dog"},
    ]
    for i, d in enumerate(docs):
        svc.index_doc(str(i), d)
    svc.refresh()
    yield svc
    svc.close()


class TestTermSuggester:
    def test_misspelling_corrected(self, idx):
        r = idx.search({"size": 0, "suggest": {
            "fix": {"text": "quik browm", "term": {"field": "body"}},
        }})
        sug = r["suggest"]["fix"]
        assert sug[0]["text"] == "quik"
        assert sug[0]["options"][0]["text"] == "quick"
        assert sug[1]["options"][0]["text"] == "brown"

    def test_correct_word_no_options(self, idx):
        r = idx.search({"size": 0, "suggest": {
            "fix": {"text": "quick", "term": {"field": "body"}},
        }})
        assert r["suggest"]["fix"][0]["options"] == []

    def test_freq_ranking(self, idx):
        # "quick" (3 docs) should outrank rarer same-distance candidates
        r = idx.search({"size": 0, "suggest": {
            "fix": {"text": "quickk", "term": {"field": "body"}},
        }})
        opts = r["suggest"]["fix"][0]["options"]
        assert opts[0]["text"] == "quick"
        assert opts[0]["freq"] == 3


class TestPhraseSuggester:
    def test_phrase_correction(self, idx):
        r = idx.search({"size": 0, "suggest": {
            "p": {"text": "quik brown", "phrase": {"field": "body"}},
        }})
        options = r["suggest"]["p"][0]["options"]
        assert options
        assert options[0]["text"] == "quick brown"


class TestCompletionSuggester:
    def test_prefix_completion_weight_order(self, idx):
        r = idx.search({"size": 0, "suggest": {
            "ac": {"prefix": "quick", "completion": {"field": "suggest"}},
        }})
        opts = r["suggest"]["ac"][0]["options"]
        texts = [o["text"] for o in opts]
        assert texts == ["quick fox", "quick silver"]  # weight 10 > 5
        assert opts[0]["_id"] == "0"

    def test_no_match(self, idx):
        r = idx.search({"size": 0, "suggest": {
            "ac": {"prefix": "zzz", "completion": {"field": "suggest"}},
        }})
        assert r["suggest"]["ac"][0]["options"] == []

    def test_multiple_inputs(self, idx):
        r = idx.search({"size": 0, "suggest": {
            "ac": {"prefix": "sil", "completion": {"field": "suggest"}},
        }})
        assert [o["text"] for o in r["suggest"]["ac"][0]["options"]] == ["silver"]
