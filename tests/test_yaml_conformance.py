"""YAML REST conformance: the reference's own rest-api-spec test files
executed verbatim against this engine's HTTP surface (SURVEY section 4.6.4
"passing it IS the compatibility metric").

Runner: elasticsearch_tpu/testing/yaml_runner.py
(ESClientYamlSuiteTestCase.java analog). The allowlist below is every
reference file this engine passes end-to-end; it only grows — a file
dropping out of the list is a compatibility regression.

Beyond the per-file allowlist, ``test_full_suite_floor`` (slow-marked)
sweeps the ENTIRE corpus and pins the verified passing COUNT at the
round-5 reviewer's independent sweep result (~117 of 254: 101 passing
under sweep + 16 allowlisted files that only timed out under sweep
contention). The round-5 conformance work (commit 6566772) claimed 125
files but never grew this pin, leaving the extra files without a
regression guard — the floor closes that gap and PRINTS the passing set
so it can be promoted into the explicit allowlist when the reference
checkout is available.

Requires the reference checkout at /root/reference (skipped when absent,
e.g. in a standalone distribution of this repo).
"""

import os

import pytest

BASE = "/root/reference/rest-api-spec/src/main/resources/rest-api-spec"

# the floor the full-suite sweep must not regress below (round-5 VERDICT:
# 101 sweep-passing + 16 allowlisted-but-contended = 117 verified)
FULL_SUITE_FLOOR = 117

pytestmark = pytest.mark.skipif(
    not os.path.isdir(BASE), reason="reference rest-api-spec not available")

PASSING = [
    "bulk/10_basic.yml",
    "bulk/20_list_of_strings.yml",
    "bulk/30_big_string.yml",
    "bulk/50_refresh.yml",
    "cat.aliases/30_json.yml",
    "cat.health/10_basic.yml",
    "cat.plugins/10_basic.yml",
    "cat.repositories/10_basic.yml",
    "cluster.reroute/10_basic.yml",
    "create/10_with_id.yml",
    "create/15_without_id.yml",
    "create/40_routing.yml",
    "delete/10_basic.yml",
    "delete/11_shard_header.yml",
    "delete/12_result.yml",
    "delete/20_internal_version.yml",
    "delete/25_external_version.yml",
    "delete/26_external_gte_version.yml",
    "delete/30_routing.yml",
    "delete/60_missing.yml",
    "exists/10_basic.yml",
    "exists/30_parent.yml",
    "exists/40_routing.yml",
    "exists/60_realtime_refresh.yml",
    "exists/70_defaults.yml",
    "explain/10_basic.yml",
    "explain/20_source_filtering.yml",
    "explain/30_query_string.yml",
    "get/10_basic.yml",
    "get/15_default_values.yml",
    "get/20_stored_fields.yml",
    "get/40_routing.yml",
    "get/60_realtime_refresh.yml",
    "get/80_missing.yml",
    "get/90_versions.yml",
    "get_source/10_basic.yml",
    "get_source/15_default_values.yml",
    "get_source/40_routing.yml",
    "get_source/60_realtime_refresh.yml",
    "get_source/70_source_filtering.yml",
    "get_source/80_missing.yml",
    "index/10_with_id.yml",
    "index/12_result.yml",
    "index/15_without_id.yml",
    "index/20_optype.yml",
    "index/30_internal_version.yml",
    "index/36_external_gte_version.yml",
    "index/40_routing.yml",
    "indices.clear_cache/10_basic.yml",
    "indices.delete/10_basic.yml",
    "indices.exists/10_basic.yml",
    "indices.exists_alias/10_basic.yml",
    "indices.exists_template/10_basic.yml",
    "indices.exists_type/10_basic.yml",
    "indices.forcemerge/10_basic.yml",
    "indices.get_alias/20_empty.yml",
    "indices.get_mapping/30_missing_index.yml",
    "indices.get_mapping/40_aliases.yml",
    "indices.get_mapping/60_empty.yml",
    "indices.get_settings/10_basic.yml",
    "indices.get_settings/20_aliases.yml",
    "indices.get_template/10_basic.yml",
    "indices.get_template/20_get_missing.yml",
    "indices.open/10_basic.yml",
    "indices.open/20_multiple_indices.yml",
    "indices.put_alias/all_path_options.yml",
    "indices.put_settings/all_path_options.yml",
    "indices.refresh/10_basic.yml",
    "indices.rollover/20_max_doc_condition.yml",
    "indices.stats/10_index.yml",
    "indices.stats/11_metric.yml",
    "indices.stats/12_level.yml",
    "indices.stats/14_groups.yml",
    "indices.stats/15_types.yml",
    "indices.validate_query/20_query_string.yml",
    "info/10_info.yml",
    "info/20_lucene_version.yml",
    "mget/10_basic.yml",
    "mlt/10_basic.yml",
    "nodes.info/10_basic.yml",
    "ping/10_ping.yml",
    "remote.info/10_info.yml",
    "search.aggregation/70_adjacency_matrix.yml",
    "search/110_field_collapsing.yml",
    "search/issue4895.yml",
    "snapshot.create/10_basic.yml",
    "suggest/10_basic.yml",
    "termvectors/40_versions.yml",
    "update/10_doc.yml",
    "update/11_shard_header.yml",
    "update/12_result.yml",
    "update/20_doc_upsert.yml",
    "update/22_doc_as_upsert.yml",
    "update/40_routing.yml",
    "update/80_source_filtering.yml",
]


@pytest.fixture(scope="module")
def conformance():
    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.rest.http_server import HttpServer
    from elasticsearch_tpu.testing.yaml_runner import (
        ApiSpecs,
        YamlTestClient,
        YamlTestRunner,
    )

    node = Node()
    srv = HttpServer(node, port=0)
    srv.start()
    runner = YamlTestRunner(
        ApiSpecs(BASE + "/api"),
        YamlTestClient(f"http://127.0.0.1:{srv.port}"))
    yield runner
    srv.stop()


@pytest.mark.parametrize("rel", PASSING)
def test_yaml_file(conformance, rel):
    executed = conformance.run_file(os.path.join(BASE, "test", rel))
    assert executed, f"no tests executed in {rel}"
    conformance.wipe()


@pytest.mark.slow
def test_full_suite_floor(conformance):
    """Sweep every reference YAML file; the passing count is pinned at
    FULL_SUITE_FLOOR and no allowlisted file may fail. Prints the full
    passing set (run with -s) so newly-passing files can be promoted
    into PASSING with a name-level guard."""
    test_root = os.path.join(BASE, "test")
    all_files = []
    for dirpath, _dirs, files in os.walk(test_root):
        for fn in sorted(files):
            if fn.endswith(".yml") or fn.endswith(".yaml"):
                all_files.append(os.path.relpath(
                    os.path.join(dirpath, fn), test_root))
    passed, failed = [], []
    for rel in sorted(all_files):
        try:
            if conformance.run_file(os.path.join(test_root, rel)):
                passed.append(rel)
            else:
                failed.append(rel)
        except Exception:  # noqa: BLE001 — a failing file, not a harness bug
            failed.append(rel)
        finally:
            try:
                conformance.wipe()
            except Exception:  # noqa: BLE001
                pass
    print(f"\nYAML full-suite sweep: {len(passed)}/{len(all_files)} passing")
    for rel in passed:
        print(f"  PASS {rel}")
    allowlist_regressions = sorted(set(PASSING) & set(failed))
    assert not allowlist_regressions, (
        f"allowlisted files regressed: {allowlist_regressions}")
    assert len(passed) >= FULL_SUITE_FLOOR, (
        f"full-suite passing count {len(passed)} dropped below the "
        f"pinned floor {FULL_SUITE_FLOOR}")
