"""Point-in-time scroll contexts (search/internal/ScrollContext.java,
SearchService.java:874 keep-alive contexts).

Round-4 VERDICT missing item 4 / weak item 5: scroll was a stored
search_after cursor whose results shifted with NRT refreshes — a
concurrent-write reindex could skip or duplicate docs. Scroll now pins
every shard's segment set + live masks (PinnedSegmentView) at open."""

import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.node import Node


@pytest.fixture()
def node():
    n = Node(Settings.EMPTY)
    n.create_index("src", {"settings": {"number_of_shards": 2},
                           "mappings": {"properties": {
                               "n": {"type": "integer"},
                               "kind": {"type": "keyword"}}}})
    for i in range(30):
        n.index_doc("src", f"d{i}", {"n": i, "kind": "orig"})
    n.indices["src"].refresh()
    yield n
    n.close()


def drain_scroll(node, first, page_getter=None):
    ids = [h["_id"] for h in first["hits"]["hits"]]
    sid = first["_scroll_id"]
    while True:
        page = node.scroll(sid)
        if not page["hits"]["hits"]:
            break
        ids.extend(h["_id"] for h in page["hits"]["hits"])
    return ids


class TestPointInTimeScroll:
    def test_docs_indexed_after_open_are_invisible(self, node):
        first = node.search("src", {"query": {"match_all": {}}, "size": 7},
                            scroll="1m")
        # writes + refresh AFTER the scroll opened
        for i in range(30, 40):
            node.index_doc("src", f"late{i}", {"n": i, "kind": "late"})
        node.indices["src"].refresh()
        ids = drain_scroll(node, first)
        assert sorted(ids) == sorted(f"d{i}" for i in range(30))
        assert len(ids) == len(set(ids))  # no duplicates

    def test_updates_and_deletes_do_not_shift_pages(self, node):
        """The defining PIT property: concurrent updates (delete+reinsert
        into a new segment) and deletes must neither skip nor duplicate
        docs — the scroll sees the snapshot, old values included."""
        first = node.search("src", {"query": {"match_all": {}}, "size": 5},
                            scroll="1m")
        seen = {h["_id"]: h["_source"] for h in first["hits"]["hits"]}
        sid = first["_scroll_id"]
        step = 0
        while True:
            # mutate between every page: update 3 docs, delete 2
            for i in range(step * 3, step * 3 + 3):
                node.index_doc("src", f"d{i % 30}",
                               {"n": 1000 + i, "kind": "updated"})
            node.delete_doc("src", f"d{(step * 2 + 1) % 30}")
            node.indices["src"].refresh()
            step += 1
            page = node.scroll(sid)
            if not page["hits"]["hits"]:
                break
            for h in page["hits"]["hits"]:
                assert h["_id"] not in seen, "duplicated doc across pages"
                seen[h["_id"]] = h["_source"]
        assert sorted(seen) == sorted(f"d{i}" for i in range(30))
        # every doc carries its AT-OPEN value, not the updated one
        assert all(src["kind"] == "orig" for src in seen.values())

    def test_force_merge_mid_scroll_keeps_fetching(self, node):
        first = node.search("src", {"query": {"match_all": {}}, "size": 4},
                            scroll="1m")
        node.index_doc("src", "x1", {"n": 99, "kind": "late"})
        node.indices["src"].force_merge()  # replaces the segment objects
        ids = drain_scroll(node, first)
        assert sorted(ids) == sorted(f"d{i}" for i in range(30))

    def test_clear_scroll_frees_context(self, node):
        from elasticsearch_tpu.common.errors import ResourceNotFoundException

        first = node.search("src", {"query": {"match_all": {}}, "size": 4},
                            scroll="1m")
        sid = first["_scroll_id"]
        out = node.clear_scroll([sid])
        assert out["num_freed"] == 1
        with pytest.raises(ResourceNotFoundException):
            node.scroll(sid)

    def test_keep_alive_expiry_reaps_context(self, node):
        import time as _time

        from elasticsearch_tpu.common.errors import ResourceNotFoundException

        first = node.search("src", {"query": {"match_all": {}}, "size": 4},
                            scroll="1ms")
        sid = first["_scroll_id"]
        _time.sleep(0.05)
        with pytest.raises(ResourceNotFoundException):
            node.scroll(sid)
        # opening another scroll sweeps the expired context out entirely
        node.search("src", {"query": {"match_all": {}}, "size": 4},
                    scroll="1m")
        assert sid not in node.scrolls

    def test_background_reaper_frees_expired_pins(self, node):
        """The keepAliveReaper analog must free expired contexts on TIME
        — a node that stops receiving scroll requests must not hold
        pinned segment views forever."""
        import time as _time

        first = node.search("src", {"query": {"match_all": {}}, "size": 4},
                            scroll="1ms")
        sid = first["_scroll_id"]
        _time.sleep(0.05)
        assert node._reaper.is_alive()
        assert node._reap_expired_scrolls() == 1  # the sweep the loop runs
        assert sid not in node.scrolls

    def test_from_rejected_in_scroll_context(self, node):
        from elasticsearch_tpu.common.errors import IllegalArgumentException

        with pytest.raises(IllegalArgumentException, match="from"):
            node.search("src", {"query": {"match_all": {}}, "size": 4,
                                "from": 5}, scroll="1m")


class TestConcurrentWriteReindex:
    def test_reindex_is_point_in_time(self, node):
        """Reindex over a source receiving concurrent writes must copy
        exactly the docs visible at start — no skips, no dups, no
        torn values (VERDICT done-criterion for this item)."""
        from elasticsearch_tpu.index import reindex as rx

        node.create_index("dst", {"mappings": {"properties": {
            "n": {"type": "integer"}, "kind": {"type": "keyword"}}}})

        orig_scan = rx._scan_batches

        def interfering_scan(n, expr, query, batch_size):
            # between every yielded batch: new docs, updates, deletes
            step = [0]
            for batch in orig_scan(n, expr, query, batch_size):
                yield batch
                i = step[0]
                node.index_doc("src", f"new{i}", {"n": 500 + i,
                                                  "kind": "new"})
                node.index_doc("src", f"d{i % 30}", {"n": 900 + i,
                                                     "kind": "updated"})
                node.delete_doc("src", f"d{(i + 7) % 30}")
                node.indices["src"].refresh()
                step[0] += 1

        rx._scan_batches, restore = interfering_scan, orig_scan
        try:
            out = rx.reindex(node, {"source": {"index": "src", "size": 5},
                                    "dest": {"index": "dst"}})
        finally:
            rx._scan_batches = restore
        assert out["created"] == 30
        assert not out["failures"]
        node.indices["dst"].refresh()
        r = node.search("dst", {"query": {"match_all": {}}, "size": 100})
        ids = [h["_id"] for h in r["hits"]["hits"]]
        assert sorted(ids) == sorted(f"d{i}" for i in range(30))
        # values are the AT-START snapshot (no torn/updated reads)
        assert all(h["_source"]["kind"] == "orig"
                   for h in r["hits"]["hits"])


class TestLazyMaterialization:
    """The pit context materializes a lazily extended PREFIX, not
    O(corpus) DocRefs at open (ISSUE 2 satellite)."""

    def test_open_does_not_materialize_whole_corpus(self, node):
        for i in range(200):
            node.index_doc("big", f"b{i}", {"n": i})
        node.indices["big"].refresh()
        first = node.search("big", {"query": {"match_all": {}}, "size": 3},
                            scroll="1m")
        ctx = node.scrolls[first["_scroll_id"]]
        assert len(ctx["entries"]) < 200  # only a prefix at open
        ids = drain_scroll(node, first)
        assert sorted(ids) == sorted(f"b{i}" for i in range(200))
        assert len(ids) == len(set(ids))  # no dups across extensions
        assert len(ctx["entries"]) == 200  # fully drained by the end

    def test_lazy_pages_are_exact_under_sort(self, node):
        first = node.search("src", {"query": {"match_all": {}}, "size": 4,
                                    "sort": [{"n": "desc"}]}, scroll="1m")
        ids = drain_scroll(node, first)
        assert ids == [f"d{i}" for i in range(29, -1, -1)]

    def test_lazy_pages_exact_with_ties(self, node):
        # every doc shares the same sort key: extension rounds must not
        # skip or duplicate across tie-heavy page boundaries
        first = node.search("src", {"query": {"match_all": {}}, "size": 4,
                                    "sort": [{"kind": "asc"}]}, scroll="1m")
        ids = drain_scroll(node, first)
        assert len(ids) == 30
        assert len(set(ids)) == 30
