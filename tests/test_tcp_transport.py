"""TCP transport tests: frame protocol, in-process socket hubs, and a real
3-OS-process cluster (create index -> replicated writes -> search ->
node kill -> failover) — the SURVEY §5.8 DCN control-plane requirement.

Role models: TcpTransport framing/request-response
(core/.../transport/TcpTransport.java:121, TcpHeader.java:30), and the
multi-node integration style of InternalTestCluster but across real OS
processes."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from elasticsearch_tpu.common.errors import (
    IllegalArgumentException,
    NodeNotConnectedException,
)
from elasticsearch_tpu.transport.local import TransportService
from elasticsearch_tpu.transport.tcp import (
    KIND_ERROR,
    KIND_REQUEST,
    KIND_RESPONSE,
    TcpTransportHub,
    _encode,
    _read_frame,
)


class TestWireFormat:
    def test_roundtrip(self):
        import io
        import socket

        a, b = socket.socketpair()
        try:
            frame = _encode(KIND_REQUEST, 42, {"src": "n1", "action": "x",
                                               "payload": {"v": [1, 2]}})
            a.sendall(frame)
            kind, req_id, body = _read_frame(b)
            assert (kind, req_id) == (KIND_REQUEST, 42)
            assert body["payload"] == {"v": [1, 2]}
        finally:
            a.close()
            b.close()

    def test_numpy_payloads_serialize(self):
        frame = _encode(KIND_RESPONSE, 1, {
            "result": {"count": np.int64(3), "score": np.float32(1.5),
                       "arr": np.arange(3)}})
        assert b"1.5" in frame


def make_pair():
    hub_a = TcpTransportHub()
    hub_b = TcpTransportHub()
    svc_a = TransportService("a", hub_a)
    svc_b = TransportService("b", hub_b)
    hub_a.add_peer("b", "127.0.0.1", hub_b.port)
    hub_b.add_peer("a", "127.0.0.1", hub_a.port)
    return hub_a, hub_b, svc_a, svc_b


class TestSocketHub:
    def test_request_response(self):
        hub_a, hub_b, svc_a, svc_b = make_pair()
        try:
            svc_b.register_handler("echo", lambda p, src: {"got": p,
                                                           "from": src})
            out = svc_a.send_request("b", "echo", {"x": 1})
            assert out == {"got": {"x": 1}, "from": "a"}
        finally:
            hub_a.close()
            hub_b.close()

    def test_remote_error_propagates_typed(self):
        hub_a, hub_b, svc_a, svc_b = make_pair()
        try:
            def boom(p, src):
                raise IllegalArgumentException("bad arg over the wire")

            svc_b.register_handler("boom", boom)
            with pytest.raises(IllegalArgumentException, match="over the wire"):
                svc_a.send_request("b", "boom", {})
        finally:
            hub_a.close()
            hub_b.close()

    def test_nested_rpc_no_deadlock(self):
        """b's handler calls back into a while a waits on b (join->publish
        pattern); per-request handler threads must prevent deadlock."""
        hub_a, hub_b, svc_a, svc_b = make_pair()
        try:
            svc_a.register_handler("pong", lambda p, src: {"pong": True})

            def ping(p, src):
                back = svc_b.send_request("a", "pong", {})
                return {"nested": back}

            svc_b.register_handler("ping", ping)
            out = svc_a.send_request("b", "ping", {})
            assert out == {"nested": {"pong": True}}
        finally:
            hub_a.close()
            hub_b.close()

    def test_unknown_peer(self):
        hub_a = TcpTransportHub()
        svc_a = TransportService("a", hub_a)
        try:
            with pytest.raises(NodeNotConnectedException):
                svc_a.send_request("ghost", "x", {})
        finally:
            hub_a.close()

    def test_dead_peer(self):
        hub_a, hub_b, svc_a, svc_b = make_pair()
        hub_b.close()
        try:
            with pytest.raises(NodeNotConnectedException):
                svc_a.send_request("b", "echo", {})
        finally:
            hub_a.close()

    def test_concurrent_requests_one_connection(self):
        import threading

        hub_a, hub_b, svc_a, svc_b = make_pair()
        try:
            svc_b.register_handler("sq", lambda p, src: p["n"] * p["n"])
            results = {}

            def call(n):
                results[n] = svc_a.send_request("b", "sq", {"n": n})

            threads = [threading.Thread(target=call, args=(n,))
                       for n in range(16)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert results == {n: n * n for n in range(16)}
        finally:
            hub_a.close()
            hub_b.close()


class Worker:
    def __init__(self, name):
        self.name = name
        script = os.path.join(os.path.dirname(__file__),
                              "tcp_cluster_worker.py")
        self.proc = subprocess.Popen(
            [sys.executable, script, name, "0"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True, bufsize=1)
        ready = json.loads(self._readline(timeout=90))
        assert ready.get("ready")
        self.port = ready["port"]

    def _readline(self, timeout=60):
        import select

        r, _, _ = select.select([self.proc.stdout], [], [], timeout)
        if not r:
            raise TimeoutError(f"worker {self.name} silent")
        return self.proc.stdout.readline()

    def call(self, op, **kw):
        self.proc.stdin.write(json.dumps({"op": op, **kw}) + "\n")
        self.proc.stdin.flush()
        resp = json.loads(self._readline())
        if not resp.get("ok"):
            raise RuntimeError(f"{self.name} {op}: {resp.get('error')}")
        return resp

    def kill(self):
        self.proc.kill()
        self.proc.wait()

    def stop(self):
        if self.proc.poll() is None:
            try:
                self.call("exit")
            except Exception:
                pass
            self.proc.wait(timeout=10)


@pytest.mark.slow
class TestThreeProcessCluster:
    def test_cluster_lifecycle_and_failover(self):
        workers = {}
        try:
            for name in ("n1", "n2", "n3"):
                workers[name] = Worker(name)
            # full-mesh address book
            for a in workers.values():
                for b in workers.values():
                    if a is not b:
                        a.call("add_peer", node=b.name, port=b.port)
            workers["n1"].call("bootstrap")
            workers["n2"].call("join", seed="n1")
            workers["n3"].call("join", seed="n1")
            st = workers["n1"].call("state")
            assert st["master"] == "n1"
            assert sorted(st["nodes"]) == ["n1", "n2", "n3"]

            workers["n1"].call(
                "create_index", index="logs",
                settings={"index": {"number_of_shards": 2,
                                    "number_of_replicas": 1}},
                mappings={"properties": {"msg": {"type": "text"}}})
            for i in range(20):
                workers["n1"].call("index", index="logs", id=str(i),
                                   doc={"msg": f"event number {i}"})
            workers["n2"].call("refresh", index="logs")
            res = workers["n2"].call(
                "search", index="logs",
                body={"query": {"match": {"msg": "event"}}, "size": 25})
            assert res["result"]["hits"]["total"] == 20

            # doc readable via another node (routing + remote GET)
            got = workers["n3"].call("get", index="logs", id="7")
            assert got["result"]["_source"]["msg"] == "event number 7"

            # kill a data node; master detects + promotes; search survives
            workers["n2"].kill()
            departed = workers["n1"].call("check_nodes")["departed"]
            assert "n2" in departed
            res = workers["n3"].call(
                "search", index="logs",
                body={"query": {"match": {"msg": "event"}}, "size": 25})
            assert res["result"]["hits"]["total"] == 20
            routing = workers["n1"].call("routing")["routing"]
            for copies in routing.values():
                primaries = [c for c in copies if c["primary"]]
                assert len(primaries) == 1
                assert primaries[0]["node"] != "n2"

            # kill the MASTER: the surviving node detects the loss over
            # the socket, elects itself, and keeps serving
            workers["n1"].kill()
            new_master = workers["n3"].call("check_master")["master"]
            assert new_master == "n3"
            st = workers["n3"].call("state")
            assert st["master"] == "n3"
            assert "n1" not in st["nodes"]
            res = workers["n3"].call(
                "search", index="logs",
                body={"query": {"match": {"msg": "event"}}, "size": 25})
            assert res["result"]["hits"]["total"] == 20
        finally:
            for w in workers.values():
                try:
                    w.stop()
                except Exception:
                    w.kill()
