"""Multi-tenant overload control (ISSUE 12, docs/OVERLOAD.md):
bounded admission + 429/Retry-After, DRR tenant fairness, the brownout
ladder (fire in order, recover in reverse), the adaptive batch window,
pre-execution shedding of deadline-expired queue entries, and exact
counters under a concurrent mixed burst (the PR-8 concurrency idiom)."""

import threading
import time

import pytest

from elasticsearch_tpu.common.errors import EsRejectedExecutionException
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService
from elasticsearch_tpu.search.telemetry import set_opaque_id
from elasticsearch_tpu.testing import disruption as dis

MAPPING = {"properties": {
    "body": {"type": "text", "analyzer": "whitespace"},
    "n": {"type": "integer"},
}}


def build_index(name="adm", shards=2, **extra):
    # host plane: SearchDelayScheme's per-shard stall (the deterministic
    # service-time generator these tests meter admission with) fires on
    # the host path; admission itself is plane-agnostic — it sits at
    # dispatch before the ladder
    settings = {"index.number_of_shards": shards,
                "index.search.mesh": False,
                "index.refresh_interval": -1}
    settings.update(extra)
    idx = IndexService(name, Settings(settings), mapping=MAPPING)
    for d in range(12):
        idx.index_doc(str(d), {"body": f"w{d % 3} common", "n": d})
    idx.refresh()
    idx.search({"query": {"match": {"body": "common"}}})  # warm planes
    return idx


@pytest.fixture(autouse=True)
def _clean_schemes():
    yield
    dis.clear_search_disruptions()
    set_opaque_id(None)


QUERY = {"query": {"match": {"body": "common"}}, "size": 5}


class TestBoundedAdmission:
    def test_queue_full_rejects_429_with_retry_after(self):
        idx = build_index(**{"search.admission.max_concurrent": 1,
                             "search.queue.size": 2})
        slow = dis.SearchDelayScheme(0.25, indices=["adm"]).install()
        results = {"ok": 0, "rej": 0, "retry_after": None, "exc": None}
        lock = threading.Lock()

        def worker():
            try:
                idx.search(dict(QUERY))
                with lock:
                    results["ok"] += 1
            except EsRejectedExecutionException as e:
                with lock:
                    results["rej"] += 1
                    results["retry_after"] = getattr(e, "retry_after_s",
                                                     None)
                    results["exc"] = e

        try:
            threads = [threading.Thread(target=worker) for _ in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        finally:
            slow.remove()
            idx.close()
        assert results["ok"] >= 1 and results["rej"] >= 1
        assert results["ok"] + results["rej"] == 8
        # the reference-shaped 429 body: type + reason naming the
        # queue capacity; Retry-After rides as an attribute, never a
        # timeout, never a 5xx
        exc = results["exc"]
        assert exc.status_code == 429
        err = exc.to_dict()["error"]
        assert err["type"] == "es_rejected_execution_exception"
        assert "queue capacity [2]" in err["reason"]
        assert results["retry_after"] is not None
        assert results["retry_after"] >= 1.0

    def test_rest_429_contract_and_retry_after_header(self):
        from elasticsearch_tpu.client import Client
        from elasticsearch_tpu.node import Node
        from elasticsearch_tpu.rest.controller import (
            collect_response_headers,
        )

        node = Node(Settings({"cluster.name": "adm-rest"}))
        try:
            c = Client(node)
            c.index("ridx", "1", {"body": "hello"})
            node.indices["ridx"].refresh()
            qp = dis.QueuePressureScheme(
                occupancy=2000, block_slots=10_000,
                indices=["ridx"]).install()
            status, payload = c.search(
                "ridx", {"query": {"match": {"body": "hello"}}})
            headers = collect_response_headers()
            assert status == 429
            assert payload["status"] == 429
            assert payload["error"]["type"] == \
                "es_rejected_execution_exception"
            assert "queue capacity" in payload["error"]["reason"]
            # the error body stays reference-shaped: the retry hint is
            # the HTTP header, not a body field
            assert "retry_after_s" not in payload["error"]
            assert int(headers["Retry-After"]) >= 1
            qp.remove()
            status, _ = c.search(
                "ridx", {"query": {"match": {"body": "hello"}}})
            assert status == 200
        finally:
            node.close()

    def test_msearch_rejects_per_entry_peers_unaffected(self):
        from elasticsearch_tpu.client import Client
        from elasticsearch_tpu.node import Node

        node = Node(Settings({"cluster.name": "adm-ms"}))
        try:
            c = Client(node)
            c.index("hot", "1", {"body": "hello"})
            c.index("cold", "1", {"body": "hello"})
            node.indices["hot"].refresh()
            node.indices["cold"].refresh()
            qp = dis.QueuePressureScheme(
                occupancy=2000, block_slots=10_000,
                indices=["hot"]).install()
            body = (b'{"index": "hot"}\n'
                    b'{"query": {"match": {"body": "hello"}}}\n'
                    b'{"index": "cold"}\n'
                    b'{"query": {"match": {"body": "hello"}}}\n')
            status, payload = c.perform("POST", "/_msearch", None, body)
            qp.remove()
            # the PR-4 partial-failure contract: one rejected member is
            # that member's 429 entry, its peer completes normally
            assert status == 200
            entries = payload["responses"]
            assert entries[0]["status"] == 429
            assert entries[0]["error"]["type"] == \
                "es_rejected_execution_exception"
            assert entries[1]["hits"]["total"] == 1
        finally:
            node.close()

    def test_bulk_path_untouched_under_pressure(self):
        from elasticsearch_tpu.client import Client
        from elasticsearch_tpu.node import Node

        node = Node(Settings({"cluster.name": "adm-bulk"}))
        try:
            c = Client(node)
            c.index("bidx", "1", {"body": "x"})
            qp = dis.QueuePressureScheme(
                occupancy=2000, block_slots=10_000,
                indices=["bidx"]).install()
            status, payload = c.bulk(
                '{"index": {"_index": "bidx", "_id": "2"}}\n'
                '{"body": "y"}\n')
            qp.remove()
            assert status == 200 and payload["errors"] is False
        finally:
            node.close()


class TestTenantFairness:
    def test_drr_keeps_light_tenant_interleaved(self):
        """A zipfian-hot tenant floods the queue; the light tenant's
        entries still dequeue round-robin — between any two light-tenant
        admissions at most (weight ratio + immediate-admit slack) hot
        queries pass, so the light tenant's p99 is bounded by its own
        queue, not the hot tenant's."""
        idx = build_index(**{"search.admission.max_concurrent": 1,
                             "search.queue.size": 100})
        slow = dis.SearchDelayScheme(0.01, indices=["adm"]).install()
        hot_n, light_n = 18, 4
        started = threading.Barrier(hot_n + light_n + 1)

        def client(tenant):
            set_opaque_id(tenant)
            started.wait()
            idx.search(dict(QUERY))

        threads = [threading.Thread(target=client, args=("hot",))
                   for _ in range(hot_n)]
        threads += [threading.Thread(target=client, args=("light",))
                    for _ in range(light_n)]
        try:
            for t in threads:
                t.start()
            started.wait()  # release the burst at once
            for t in threads:
                t.join()
        finally:
            slow.remove()
        log = list(idx.admission.admission_log)
        idx.close()
        light_pos = [i for i, t in enumerate(log) if t == "light"]
        assert len(light_pos) == light_n
        # DRR: equal weights alternate hot/light while both queues are
        # non-empty. The burst races admission, so allow slack for
        # entries admitted before the light queue formed — but the last
        # light query must land well before the hot flood finishes.
        assert light_pos[-1] <= 2 * light_n + 6, log

    def test_weighted_tenant_gets_proportional_share(self):
        idx = build_index(**{"search.admission.max_concurrent": 1,
                             "search.queue.size": 100,
                             "search.admission.weights": "vip:3"})
        slow = dis.SearchDelayScheme(0.005, indices=["adm"]).install()
        started = threading.Barrier(13)

        def client(tenant):
            set_opaque_id(tenant)
            started.wait()
            idx.search(dict(QUERY))

        threads = [threading.Thread(target=client, args=("vip",))
                   for _ in range(9)]
        threads += [threading.Thread(target=client, args=("std",))
                    for _ in range(3)]
        try:
            for t in threads:
                t.start()
            started.wait()
            for t in threads:
                t.join()
        finally:
            slow.remove()
        log = list(idx.admission.admission_log)
        stats = idx.admission.stats_dict()
        idx.close()
        assert stats["tenants"]["vip"]["admitted_total"] == 9
        assert stats["tenants"]["std"]["admitted_total"] == 3
        # weight 3 serves up to 3 vip entries per std entry once both
        # queues formed: std never waits behind more than 3 + slack vips
        std_pos = [i for i, t in enumerate(log) if t == "std"]
        gaps = [b - a for a, b in zip(std_pos, std_pos[1:])]
        assert all(g <= 5 for g in gaps), log


class TestQueueDisplacement:
    def test_hot_tenant_cannot_monopolize_the_queue(self):
        """Fair-share queue displacement: the overflow check is tenant-
        aware — when the queue is full of a hot tenant's entries, a
        light tenant's arrival displaces the hot tenant's newest entry
        (which gets the clean 429) instead of being rejected itself."""
        idx = build_index(**{"search.admission.max_concurrent": 1,
                             "search.queue.size": 4})
        slow = dis.SearchDelayScheme(0.05, indices=["adm"]).install()
        outcome = {"light_ok": 0, "light_rej": 0, "hot_rej": 0}
        lock = threading.Lock()

        def hot():
            set_opaque_id("hot")
            try:
                idx.search(dict(QUERY))
            except EsRejectedExecutionException:
                with lock:
                    outcome["hot_rej"] += 1

        def light():
            set_opaque_id("light")
            time.sleep(0.08)  # arrive AFTER the hot flood filled the queue
            try:
                idx.search(dict(QUERY))
                with lock:
                    outcome["light_ok"] += 1
            except EsRejectedExecutionException:
                with lock:
                    outcome["light_rej"] += 1

        threads = [threading.Thread(target=hot) for _ in range(8)]
        threads.append(threading.Thread(target=light))
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        finally:
            slow.remove()
        stats = idx.admission.stats_dict()
        idx.close()
        # the light tenant got in by displacing a hot entry — the hot
        # tenant ate the 429s, the light tenant served
        assert outcome["light_ok"] == 1 and outcome["light_rej"] == 0, \
            (outcome, stats["tenants"])
        assert outcome["hot_rej"] >= 1
        assert stats["tenants"]["light"]["admitted_total"] == 1
        assert stats["tenants"]["hot"]["rejected_total"] \
            == outcome["hot_rej"]


class TestBrownoutLadder:
    AGG_BODY = {"query": {"match": {"body": "common"}}, "size": 3,
                "aggs": {"by": {"terms": {"field": "body"}}},
                "suggest": {"s": {"text": "common",
                                  "term": {"field": "body"}}}}

    def test_steps_fire_in_order_and_recover_in_reverse(self):
        idx = build_index(**{"search.queue.size": 100})
        try:
            oracle = idx.search(dict(self.AGG_BODY))
            assert "aggregations" in oracle and "suggest" in oracle
            levels_up, levels_down = [], []
            # pressure rises through the thresholds: 0.25 / 0.5 / 0.75
            for occ in (0, 30, 60, 90):
                qp = dis.QueuePressureScheme(
                    occupancy=occ, indices=["adm"]).install()
                levels_up.append(idx.admission.refresh_level())
                qp.remove()
            for occ in (90, 60, 30, 0):
                qp = dis.QueuePressureScheme(
                    occupancy=occ, indices=["adm"]).install()
                levels_down.append(idx.admission.refresh_level())
                qp.remove()
            assert levels_up == [0, 1, 2, 3]
            assert levels_down == [3, 2, 1, 0]
            tr = idx.admission.stats_dict()["brownout_transitions"]
            assert tr["enter"] == {"1": 1, "2": 1, "3": 1}
            assert tr["exit"] == {"1": 1, "2": 1, "3": 1}
        finally:
            idx.close()

    def test_sheds_rescore_then_features_marked_and_counted(self):
        idx = build_index(**{"search.queue.size": 100})
        try:
            body = dict(self.AGG_BODY)
            body["rescore"] = {"window_size": 5, "query": {
                "rescore_query": {"match": {"body": "w1"}}}}
            # level 2: rescore shed, aggs/suggest kept
            qp = dis.QueuePressureScheme(occupancy=60,
                                         indices=["adm"]).install()
            r2 = idx.search(dict(body))
            qp.remove()
            assert "rescore" in r2["_degraded"]
            assert "forced_pruned" in r2["_degraded"]
            assert "aggregations" in r2 and "suggest" in r2
            # level 3: aggs + suggest shed too
            qp = dis.QueuePressureScheme(occupancy=90,
                                         indices=["adm"]).install()
            r3 = idx.search(dict(body))
            qp.remove()
            assert {"rescore", "aggs", "suggest"} <= set(r3["_degraded"])
            assert "aggregations" not in r3 and "suggest" not in r3
            stats = idx.admission.stats_dict()
            assert stats["brownout"]["shed_rescore_total"] == 2
            assert stats["brownout"]["shed_features_total"] == 2
            assert stats["brownout"]["forced_pruned_total"] >= 2
        finally:
            idx.close()

    def test_recovery_returns_full_precision_byte_identical(self):
        """The acceptance invariant: a drained queue returns subsequent
        queries to full-precision, full-feature responses, byte-
        identical to the unloaded oracle — including through the
        request cache (a browned-out response must not be replayed)."""
        idx = build_index(**{"search.queue.size": 100})
        try:
            oracle = idx.search(dict(self.AGG_BODY))
            qp = dis.QueuePressureScheme(occupancy=90,
                                         indices=["adm"]).install()
            degraded = idx.search(dict(self.AGG_BODY))
            assert degraded.get("_degraded")
            assert "aggregations" not in degraded
            qp.remove()
            idx.admission.refresh_level()
            healed = idx.search(dict(self.AGG_BODY))
            assert "_degraded" not in healed
            key = lambda r: ([(h["_id"], h["_score"])  # noqa: E731
                              for h in r["hits"]["hits"]],
                             r["hits"]["total"], r.get("aggregations"),
                             r.get("suggest"))
            assert key(healed) == key(oracle)
        finally:
            idx.close()

    def test_brownout_forces_pruning_eligibility(self):
        """Step 1: the mesh plane's pruning config reads the forced
        flag while pressure is above the pruned threshold and releases
        it when the queue drains."""
        from elasticsearch_tpu.parallel.plan_exec import IndexMeshSearch

        idx = build_index(shards=3, **{"search.queue.size": 100})
        try:
            if idx._mesh_search is None:
                idx._mesh_search = IndexMeshSearch(idx)
            enabled, _probe = idx._mesh_search._pruning_config()
            assert enabled is False
            qp = dis.QueuePressureScheme(occupancy=30,
                                         indices=["adm"]).install()
            idx.admission.refresh_level()
            enabled, _probe = idx._mesh_search._pruning_config()
            assert enabled is True
            qp.remove()
            idx.admission.refresh_level()
            enabled, _probe = idx._mesh_search._pruning_config()
            assert enabled is False
        finally:
            idx.close()


class TestAdaptiveBatchWindow:
    def test_window_widens_with_pressure_and_narrows_back(self):
        idx = build_index(**{"search.queue.size": 100,
                             "search.batch.window_ms": 0.2})
        try:
            base_s = idx._batcher.window_s
            assert idx.admission.effective_batch_window_s(base_s) == \
                pytest.approx(base_s)
            qp = dis.QueuePressureScheme(occupancy=50,
                                         indices=["adm"]).install()
            widened = idx.admission.effective_batch_window_s(base_s)
            assert widened > base_s
            # bounded by search.batch.max_window_ms (default 5ms)
            assert widened <= 0.005 + 1e-9
            qp2 = dis.QueuePressureScheme(occupancy=1000,
                                          indices=["adm"]).install()
            assert idx.admission.effective_batch_window_s(base_s) == \
                pytest.approx(0.005)
            qp2.remove()
            qp.remove()
            assert idx.admission.effective_batch_window_s(base_s) == \
                pytest.approx(base_s)
        finally:
            idx.close()

    def test_effective_window_gauge_exported(self):
        """The leader records the window it actually used — the
        batch_window_effective_ms gauge beside batch_size_histogram."""
        idx = build_index(**{"search.batch.window_ms": 0.5})
        try:
            qp = dis.QueuePressureScheme(occupancy=50,
                                         indices=["adm"]).install()
            barrier = threading.Barrier(4)

            def worker():
                barrier.wait()
                idx.search(dict(QUERY))

            threads = [threading.Thread(target=worker) for _ in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            qp.remove()
            gauge = idx.batch_stats.as_dict()["batch_window_effective_ms"]
            # the histogram records batches; the gauge records the
            # widened window whenever a leader collected one
            if idx.batch_stats.as_dict()["batch_window_waits_total"]:
                assert gauge > 0.5
        finally:
            idx.close()


class TestExpiredQueueShedding:
    def test_deadline_expired_entry_shed_before_execution(self):
        idx = build_index(shards=1,
                          **{"search.admission.max_concurrent": 1,
                             "search.queue.size": 10})
        # counts every query that actually reaches execution
        probe = dis.SearchDelayScheme(0.0, indices=["adm"]).install()
        slow = dis.SearchDelayScheme(0.3, indices=["adm"]).install()
        out = {}

        def occupier():
            idx.search(dict(QUERY))

        def expiring():
            time.sleep(0.05)  # let the occupier take the slot
            out["resp"] = idx.search(dict(QUERY, timeout="50ms"))

        t0 = threading.Thread(target=occupier)
        t1 = threading.Thread(target=expiring)
        try:
            t0.start()
            t1.start()
            t0.join()
            t1.join()
        finally:
            slow.remove()
        executed = probe.hits
        probe.remove()
        stats = idx.admission.stats_dict()
        idx.close()
        resp = out["resp"]
        # shed PRE-execution: timed-out partial response, zero hits,
        # and the query never reached the shard/plane path
        assert resp["timed_out"] is True
        assert resp["hits"]["hits"] == []
        assert resp["_degraded"] == ["expired_in_queue"]
        assert stats["expired_in_queue_total"] == 1
        assert executed == 1  # only the occupier executed
        assert stats["admitted_total"] == 2  # warm-up + occupier

    def test_expired_shed_honors_allow_partial_false(self):
        from elasticsearch_tpu.common.errors import (
            SearchPhaseExecutionException,
        )

        idx = build_index(**{"search.admission.max_concurrent": 1,
                             "search.queue.size": 10})
        slow = dis.SearchDelayScheme(0.3, indices=["adm"]).install()
        out = {}

        def occupier():
            idx.search(dict(QUERY))

        def expiring():
            time.sleep(0.05)
            try:
                idx.search(dict(QUERY, timeout="50ms",
                                allow_partial_search_results=False))
            except Exception as e:  # noqa: BLE001 — asserted below
                out["exc"] = e

        t0 = threading.Thread(target=occupier)
        t1 = threading.Thread(target=expiring)
        try:
            t0.start()
            t1.start()
            t0.join()
            t1.join()
        finally:
            slow.remove()
            idx.close()
        assert isinstance(out.get("exc"), SearchPhaseExecutionException)


class TestExactCountersUnderBurst:
    def test_admitted_rejected_expired_partition_offered(self):
        """PR-8 concurrency idiom: a mixed burst across tenants; every
        offered query ends in exactly one of admitted / rejected /
        expired-in-queue, globally and per tenant."""
        idx = build_index(**{"search.admission.max_concurrent": 2,
                             "search.queue.size": 6})
        base = idx.admission.stats_dict()
        slow = dis.SearchDelayScheme(0.02, indices=["adm"]).install()
        n_threads, per_thread = 6, 4
        counts = [dict(ok=0, rej=0) for _ in range(n_threads)]

        def client(tid):
            set_opaque_id(f"tenant{tid % 3}")
            for i in range(per_thread):
                try:
                    body = dict(QUERY)
                    if (tid + i) % 5 == 0:
                        body["timeout"] = "30ms"
                    r = idx.search(body)
                    assert not r["_shards"]["failed"]
                    counts[tid]["ok"] += 1
                except EsRejectedExecutionException:
                    counts[tid]["rej"] += 1

        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(n_threads)]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        finally:
            slow.remove()
        stats = idx.admission.stats_dict()
        idx.close()
        offered = n_threads * per_thread
        client_ok = sum(c["ok"] for c in counts)
        client_rej = sum(c["rej"] for c in counts)
        assert client_ok + client_rej == offered
        # a shed (expired-in-queue) query returns a timed-out partial
        # response, so it counts ok client-side but expired in stats
        # (deltas: build_index's warm-up search admitted once already)
        d_admitted = stats["admitted_total"] - base["admitted_total"]
        d_expired = (stats["expired_in_queue_total"]
                     - base["expired_in_queue_total"])
        d_rejected = stats["rejected_total"] - base["rejected_total"]
        assert d_admitted + d_expired == client_ok
        assert d_rejected == client_rej
        assert stats["in_flight"] == 0 and stats["queued"] == 0
        per_tenant = stats["tenants"]
        assert sum(b["admitted_total"] for b in per_tenant.values()) \
            == stats["admitted_total"]
        assert sum(b["rejected_total"] for b in per_tenant.values()) \
            == stats["rejected_total"]


class TestAdmissionConfig:
    def test_dynamic_cluster_override_and_explicit_clear(self):
        """search.queue.* / search.admission.* follow the explicitness
        contract: an explicit cluster value wins over the index's
        creation-time Settings; clearing it hands control back."""
        from elasticsearch_tpu.node import Node

        node = Node(Settings({"cluster.name": "adm-dyn"}))
        try:
            node.create_index("dyn", {"settings": {
                "number_of_shards": 1}})
            adm = node.indices["dyn"].admission
            assert adm._queue_size() == 1000
            node.put_cluster_settings({"transient": {
                "search.queue.size": 7,
                "search.admission.max_concurrent": 3}})
            assert adm._queue_size() == 7
            assert adm._max_concurrent() == 3
            # an index created AFTER the update is seeded with the live
            # value (create_index seeding, like search.batch.*)
            node.create_index("dyn2", {"settings": {
                "number_of_shards": 1}})
            assert node.indices["dyn2"].admission._queue_size() == 7
            node.put_cluster_settings({"transient": {
                "search.queue.size": None,
                "search.admission.max_concurrent": None}})
            assert adm._queue_size() == 1000
        finally:
            node.close()

    def test_rest_search_pool_sized_from_queue_setting(self):
        from elasticsearch_tpu.node import Node

        node = Node(Settings({"cluster.name": "adm-pool",
                              "search.queue.size": 123}))
        try:
            pool = node.thread_pool.executor("search")
            assert pool.queue_size == 123
            # both backpressure points move together under a dynamic
            # update, and an explicit clear reverts to the node file
            node.put_cluster_settings({"transient": {
                "search.queue.size": 77}})
            assert pool.queue_size == 77
            assert pool._queue.maxsize == 77
            node.put_cluster_settings({"transient": {
                "search.queue.size": None}})
            assert pool.queue_size == 123
        finally:
            node.close()

    def test_disabled_admission_is_inert(self):
        idx = build_index(**{"search.admission.enabled": False,
                             "search.admission.max_concurrent": 1,
                             "search.queue.size": 1})
        qp = dis.QueuePressureScheme(occupancy=2000, block_slots=10_000,
                                     indices=["adm"]).install()
        try:
            r = idx.search(dict(QUERY))
            assert r["hits"]["hits"]
            assert "_degraded" not in r
            assert idx.admission.stats_dict()["rejected_total"] == 0
        finally:
            qp.remove()
            idx.close()

    def test_stats_block_shape(self):
        idx = build_index()
        try:
            block = idx.search_stats()["admission"]
            for key in ("queue_capacity", "queued", "in_flight",
                        "admitted_total", "rejected_total",
                        "expired_in_queue_total", "brownout_level",
                        "brownout", "brownout_transitions",
                        "retry_after_s", "drain_rate_qps", "tenants"):
                assert key in block, key
            # node-level merge: the block sums across indices
            from elasticsearch_tpu.search.telemetry import (
                merge_phase_stats,
            )

            merged = merge_phase_stats([idx.search_stats(),
                                        idx.search_stats()])
            assert merged["admission"]["admitted_total"] == \
                2 * block["admitted_total"]
        finally:
            idx.close()
