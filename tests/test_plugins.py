"""Plugin SPI tests.

Mirrors the reference's extension system (core/.../plugins/): Plugin base
+ per-area SPIs discovered by PluginsService and wired through the Node.
"""

import pytest

from elasticsearch_tpu.common.errors import IllegalArgumentException
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.plugins import Plugin, PluginsService
from elasticsearch_tpu.plugins.examples import ExamplePlugin


@pytest.fixture()
def node():
    n = Node(plugins=[ExamplePlugin])
    yield n
    n.close()


class TestPluginsService:
    def test_loads_from_settings_classpath(self):
        n = Node(Settings({"node.plugins":
                           ["elasticsearch_tpu.plugins.examples:ExamplePlugin"]}))
        assert [p["name"] for p in n.plugins_service.info()] == ["example-plugin"]
        n.close()

    def test_bad_classpath_rejected(self):
        with pytest.raises(IllegalArgumentException):
            Node(Settings({"node.plugins": ["no.such.module:Nope"]}))

    def test_duplicate_registration_rejected(self):
        n = Node(plugins=[ExamplePlugin])
        try:
            with pytest.raises(IllegalArgumentException, match="already registered"):
                PluginsService(n, None, [ExamplePlugin])
        finally:
            n.close()

    def test_failed_install_rolls_back(self):
        from elasticsearch_tpu.search.query_dsl import CUSTOM_QUERY_PARSERS

        class Broken(Plugin):
            name = "broken"

            def get_queries(self):
                return {"term_prefix": lambda b: None}

            def get_processors(self):
                raise RuntimeError("boom")

        with pytest.raises(RuntimeError):
            Node(plugins=[Broken])
        # the partial query registration must not leak
        assert "term_prefix" not in CUSTOM_QUERY_PARSERS

    def test_close_uninstalls(self):
        from elasticsearch_tpu.search.query_dsl import CUSTOM_QUERY_PARSERS

        n = Node(plugins=[ExamplePlugin])
        assert "term_prefix" in CUSTOM_QUERY_PARSERS
        n.close()
        assert "term_prefix" not in CUSTOM_QUERY_PARSERS

    def test_on_node_start_called(self, node):
        assert node.plugins_service.plugins[0].started_on == node.node_name


class TestSPIHooks:
    def test_custom_query(self, node):
        node.create_index("idx")
        node.index_doc("idx", "1", {"name": "elastic"})
        node.index_doc("idx", "2", {"name": "plastic"})
        node.indices["idx"].refresh()
        r = node.search("idx", {"query": {"term_prefix": {"name": "ela"}}})
        assert [h["_id"] for h in r["hits"]["hits"]] == ["1"]

    def test_custom_aggregation(self, node):
        node.create_index("idx")
        for i in range(4):
            node.index_doc("idx", str(i), {"v": i})
        node.indices["idx"].refresh()
        r = node.search("idx", {"size": 0, "aggs": {
            "scaled": {"doc_count_times": {"factor": 2.5}}}})
        assert r["aggregations"]["scaled"]["value"] == 10.0

    def test_custom_field_type(self, node):
        node.create_index("idx", {"mappings": {"properties": {
            "code": {"type": "reversed_keyword"}}}})
        node.index_doc("idx", "1", {"code": "abc"})
        node.indices["idx"].refresh()
        r = node.search("idx", {"query": {"term": {"code": "abc"}}})
        assert r["hits"]["total"] == 1
        r = node.search("idx", {"size": 0, "aggs": {
            "codes": {"terms": {"field": "code"}}}})
        assert r["aggregations"]["codes"]["buckets"][0]["key"] == "cba"

    def test_custom_token_filter_in_custom_analyzer(self, node):
        node.create_index("idx", {
            "settings": {"index.analysis.analyzer.rev.type": "custom",
                         "index.analysis.analyzer.rev.tokenizer": "standard",
                         "index.analysis.analyzer.rev.filter": ["reverse_example"]},
            "mappings": {"properties": {
                "t": {"type": "text", "analyzer": "rev"}}}})
        node.index_doc("idx", "1", {"t": "hello"})
        node.indices["idx"].refresh()
        r = node.search("idx", {"query": {"term": {"t": "olleh"}}})
        assert r["hits"]["total"] == 1

    def test_custom_ingest_processor(self, node):
        node.ingest.put_pipeline("tagger", {
            "processors": [{"add_tag": {"tag": "seen"}}]})
        node.index_doc("idx2", "1", {"msg": "x"}, pipeline="tagger")
        g = node.get_doc("idx2", "1")
        assert g["_source"]["tags"] == ["seen"]

    def test_custom_script_engine(self, node):
        node.create_index("idx")
        node.index_doc("idx", "1", {"n": 21})
        node.indices["idx"].refresh()
        r = node.search("idx", {"query": {"match_all": {}}, "script_fields": {
            "doubled": {"script": {"lang": "twice", "source": "n"}}}})
        assert r["hits"]["hits"][0]["fields"]["doubled"] == [42.0]

    def test_custom_rest_handler(self, node):
        from elasticsearch_tpu.rest.controller import RestController

        controller = RestController(node)
        status, body = controller.dispatch("GET", "/_example/ping", {}, None)
        assert status == 200 and body["pong"] is True

    def test_custom_repository_type(self, node):
        r = node.snapshots.put_repository("mem", {"type": "memory",
                                                  "settings": {}})
        assert r["acknowledged"] is True
        assert node.snapshots.repositories["mem"].blobs == {}
        with pytest.raises(IllegalArgumentException):
            node.snapshots.put_repository("bad", {"type": "nope"})

    def test_cat_plugins_and_node_info(self, node):
        info = node.node_info()
        plugins = info["nodes"][node.node_id]["plugins"]
        assert plugins[0]["name"] == "example-plugin"


class TestPluginIsolation:
    def test_unknown_query_still_rejected_without_plugin(self):
        from elasticsearch_tpu.common.errors import ParsingException

        n = Node()
        n.create_index("idx")
        n.index_doc("idx", "1", {"a": 1})
        n.indices["idx"].refresh()
        with pytest.raises(ParsingException):
            n.search("idx", {"query": {"term_prefix": {"a": "x"}}})
        n.close()
